"""tpudml.mpmd: stage-group topology, p2p wire contract, re-mesh
bookkeeping, and the meshless fixture replay — all jax-free.

Mirrors ``tests/test_elastic.py``'s split: controller/topology semantics
are pinned here with pure-python structures, socketpair channels, and
stub replanners (seconds, no backend); the e2e drill with real gloo
worlds and SIGKILL-grade rank death lives in
``tests/test_mpmd_pipeline.py``.
"""

import json
import os
import socket
import struct
import sys
import threading
import zlib
from pathlib import Path

import numpy as np
import pytest

from tpudml.comm.p2p import (
    FRAME_MAGIC,
    TAG_ACT,
    DrainBarrier,
    FramingError,
    PeerDeadError,
    channel_pair,
    p2p_wire_bytes,
    recv_frame,
    send_frame,
)
from tpudml.comm.timing import collective_wire_bytes
from tpudml.mpmd import (
    PipelineSpec,
    StageQuorumError,
    StageSpec,
    boundary_plan,
    common_resume_step,
    drain_marker_path,
    drain_order,
    read_drain_markers,
    replace_pipeline,
    stage_ckpt_dir,
    warmup_microbatches,
    write_wiring,
)

FIXTURES = Path(__file__).parent / "mpmd_fixtures"

PY = sys.executable


def _pipeline(**kw) -> PipelineSpec:
    """The drill's canonical 2-stage×2-dp pipeline: bf16 trunk chunking
    finer than the f32 head."""
    d = dict(
        stages=(
            StageSpec("trunk", dp=2, microbatches=2, dtype="bfloat16"),
            StageSpec("head", dp=2, microbatches=1, dtype="float32"),
        ),
        global_batch=8,
    )
    d.update(kw)
    return PipelineSpec(**d)


# ------------------------------------------------------------- partition


def test_slot_layout_contiguous_per_stage():
    p = _pipeline()
    assert p.total_slots == 4
    assert list(p.stage_slots(0)) == [0, 1]
    assert list(p.stage_slots(1)) == [2, 3]
    assert p.slot_of(1, 0) == 2
    assert [p.locate(s) for s in range(4)] == [
        (0, 0), (0, 1), (1, 0), (1, 1),
    ]
    with pytest.raises(ValueError, match="out of range"):
        p.locate(4)


def test_spec_validation_rejects_bad_partitions():
    with pytest.raises(ValueError, match="duplicate stage names"):
        _pipeline(stages=(StageSpec("a"), StageSpec("a")))
    with pytest.raises(ValueError, match="not divisible"):
        _pipeline(stages=(StageSpec("a", microbatches=3),), global_batch=8)
    with pytest.raises(ValueError, match="dp=3"):
        _pipeline(
            stages=(StageSpec("a", dp=3, microbatches=2),), global_batch=8
        )
    with pytest.raises(ValueError, match="min_world"):
        _pipeline(stages=(StageSpec("a", dp=2, min_world=3),))


def test_capability_table_rejects_unsupported_compositions():
    """MPMD×MoE-aux-loss, MPMD×fused-xent, MPMD×serve are table
    rejections with the machine-readable mpmd_* messages — the planner
    prunes them with receipts instead of discovering crashes."""
    from tpudml.capabilities import TABLE, CompositionError

    for key, kw in [
        ("mpmd_moe_aux_loss", dict(moe_experts=4)),
        ("mpmd_fused_xent_head", dict(fused_xent=True)),
    ]:
        with pytest.raises(CompositionError) as ei:
            _pipeline(stages=(StageSpec("a", **kw), StageSpec("b")))
        assert str(ei.value) == TABLE[key].message, key
    with pytest.raises(CompositionError) as ei:
        _pipeline(serve=True)
    assert str(ei.value) == TABLE["mpmd_serve"].message


def test_pipeline_dict_roundtrip():
    p = _pipeline()
    assert PipelineSpec.from_dict(p.to_dict()) == p
    assert PipelineSpec.from_dict(
        json.loads(json.dumps(p.to_dict()))
    ) == p


# ------------------------------------------------------ boundary dataflow


def test_boundary_plan_partitions_every_global_row_once():
    p = _pipeline()
    plan = boundary_plan(p, 0)
    # Contiguous cover of [0, global_batch) with no overlap, sorted.
    assert [t.index for t in plan] == list(range(len(plan)))
    covered = sorted(t.rows for t in plan)
    assert covered[0][0] == 0 and covered[-1][1] == p.global_batch
    for (_, hi), (lo, _) in zip(covered, covered[1:]):
        assert hi == lo
    # Both sides derive the identical list (it IS the wire schedule):
    # the src slice and dst slice of every transfer are the same rows.
    for t in plan:
        slo, shi = p.row_interval(0, t.src_microbatch, t.src_rank)
        dlo, dhi = p.row_interval(1, t.dst_microbatch, t.dst_rank)
        assert (slo + t.src_rows[0], slo + t.src_rows[1]) == t.rows
        assert (dlo + t.dst_rows[0], dlo + t.dst_rows[1]) == t.rows
        assert t.edge == f"s0r{t.src_rank}->s1r{t.dst_rank}"
    with pytest.raises(ValueError, match="no boundary"):
        boundary_plan(p, 1)


def test_warmup_rows_formula_heterogeneous_and_homogeneous():
    # Hetero: trunk chunks 4×, head 2× — the homogeneous S-1-s rule
    # would say 1, but the head's first forward needs 4 rows = 2 trunk
    # microbatches in flight.
    p = _pipeline(
        stages=(
            StageSpec("trunk", microbatches=4),
            StageSpec("head", microbatches=2),
        ),
    )
    assert warmup_microbatches(p, 0) == 2
    assert warmup_microbatches(p, 1) == 0
    # Homogeneous 3-stage reduces to the classic S-1-s.
    q = PipelineSpec(
        stages=(
            StageSpec("a", microbatches=4),
            StageSpec("b", microbatches=4),
            StageSpec("c", microbatches=4),
        ),
        global_batch=8,
    )
    assert [warmup_microbatches(q, s) for s in range(3)] == [2, 1, 0]
    with pytest.raises(ValueError, match="no stage"):
        warmup_microbatches(q, 3)


# ---------------------------------------------------- re-mesh bookkeeping


def test_replace_pipeline_preserves_survivor_order():
    p = _pipeline()
    shrunk, slot_map = replace_pipeline(p, {3})
    assert [st.dp for st in shrunk.stages] == [2, 1]
    assert slot_map == {0: 0, 1: 1, 2: 2}
    # A stage-0 death renumbers the downstream slots.
    shrunk2, slot_map2 = replace_pipeline(p, {0})
    assert [st.dp for st in shrunk2.stages] == [1, 2]
    assert slot_map2 == {1: 0, 2: 1, 3: 2}
    with pytest.raises(ValueError, match="unknown slots"):
        replace_pipeline(p, {9})


def test_replace_pipeline_quorum_and_divisibility():
    p = _pipeline(
        stages=(
            StageSpec("trunk", dp=2, microbatches=2, min_world=2),
            StageSpec("head", dp=2),
        ),
    )
    with pytest.raises(StageQuorumError, match="min_world=2"):
        replace_pipeline(p, {1})
    # Survivors that no longer divide the microbatch rows are an
    # infeasible shrink (the spec validation re-runs on construction).
    q = _pipeline(
        stages=(
            StageSpec("trunk", dp=3, microbatches=3),
            StageSpec("head", dp=1),
        ),
        global_batch=9,
    )
    with pytest.raises(ValueError, match="not divisible"):
        replace_pipeline(q, {0})


def test_drain_order_deepest_stage_first_victims_excluded():
    p = _pipeline()
    assert drain_order(p, {3}) == ((1, 0), (0, 0), (0, 1))
    assert drain_order(p, {0}) == ((1, 0), (1, 1), (0, 1))


# ------------------------------------------------------------ wire frames


def test_frame_roundtrip_preserves_dtype_and_shape():
    a, b = socket.socketpair()
    try:
        for arr in (
            np.arange(12, dtype=np.float32).reshape(3, 4),
            np.array([1, -2, 3], dtype=np.int32),
        ):
            send_frame(a, arr, step=7, microbatch=2, tag=TAG_ACT,
                       edge="s0r0->s1r0")
            out = recv_frame(b, step=7, microbatch=2, tag=TAG_ACT,
                             edge="s0r0->s1r0")
            assert out.dtype == arr.dtype and out.shape == arr.shape
            np.testing.assert_array_equal(out, arr)
    finally:
        a.close()
        b.close()


def test_framing_mismatch_keeps_stream_aligned():
    """A mismatched frame raises FramingError AFTER consuming its
    payload, so the next recv on the same channel still parses — the
    error is catchable without poisoning the stream."""
    a, b = socket.socketpair()
    try:
        x = np.ones((2,), np.float32)
        send_frame(a, x, step=0, microbatch=0, tag=TAG_ACT, edge="e")
        send_frame(a, 2 * x, step=1, microbatch=0, tag=TAG_ACT, edge="e")
        with pytest.raises(FramingError, match="frame mismatch"):
            recv_frame(b, step=5, microbatch=0, tag=TAG_ACT, edge="e")
        out = recv_frame(b, step=1, microbatch=0, tag=TAG_ACT, edge="e")
        np.testing.assert_array_equal(out, 2 * x)
    finally:
        a.close()
        b.close()


def test_corrupt_payload_crc_is_a_framing_error():
    a, b = socket.socketpair()
    try:
        payload = b"\x00" * 8
        header = json.dumps(
            {"v": 1, "step": 0, "microbatch": 0, "tag": TAG_ACT,
             "edge": "e", "dtype": "float32", "shape": [2],
             "nbytes": len(payload), "crc": zlib.crc32(payload) ^ 1},
            sort_keys=True, separators=(",", ":"),
        ).encode()
        a.sendall(struct.pack("!II", FRAME_MAGIC, len(header))
                  + header + payload)
        with pytest.raises(FramingError, match="CRC mismatch"):
            recv_frame(b, step=0, microbatch=0, tag=TAG_ACT, edge="e")
    finally:
        a.close()
        b.close()


def test_peer_death_is_membership_not_a_traceback():
    ch_a, ch_b = channel_pair("s0r0->s1r0", timeout_s=5.0)
    ch_a.close()
    with pytest.raises(PeerDeadError) as ei:
        ch_b.recv(step=0, microbatch=0, tag=TAG_ACT)
    assert ei.value.edge == "s0r0->s1r0"
    ch_b.close()


def test_p2p_priced_in_shared_wire_model():
    # An MPMD edge ships its payload exactly once — the "p2p" kind in
    # the same table the planner and static analyzer score with.
    assert p2p_wire_bytes(1024) == collective_wire_bytes("p2p", 1024, 2)
    assert p2p_wire_bytes(1024) == 1024


# ----------------------------------------------------------- drain barrier


def _barrier_trio():
    """A dp=3 stage's ctl star: hub (local rank 0) + two leaves."""
    h1, l1 = channel_pair("ctl:s0r1", timeout_s=5.0)
    h2, l2 = channel_pair("ctl:s0r2", timeout_s=5.0)
    hub = DrainBarrier(hub=True, channels={1: h1, 2: h2})
    leaf1 = DrainBarrier(hub=False, channels={1: l1})
    leaf2 = DrainBarrier(hub=False, channels={2: l2})
    return hub, leaf1, leaf2, (h1, h2, l1, l2)


def _vote_all(parts, votes, step=0):
    out = {}

    def run(name, barrier, ok):
        out[name] = barrier.vote(step, ok=ok)

    ts = [
        threading.Thread(target=run, args=(n, b, v))
        for (n, b), v in zip(parts.items(), votes)
    ]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=10)
    return out


def test_drain_barrier_unanimous_ok():
    hub, leaf1, leaf2, chans = _barrier_trio()
    out = _vote_all({"hub": hub, "l1": leaf1, "l2": leaf2},
                    [True, True, True])
    assert out == {"hub": True, "l1": True, "l2": True}
    for c in chans:
        c.close()


def test_drain_barrier_single_drain_vote_vetoes_everyone():
    hub, leaf1, leaf2, chans = _barrier_trio()
    out = _vote_all({"hub": hub, "l1": leaf1, "l2": leaf2},
                    [True, False, True])
    assert out == {"hub": False, "l1": False, "l2": False}
    for c in chans:
        c.close()


def test_drain_barrier_peer_death_counts_as_drain():
    hub, leaf1, leaf2, chans = _barrier_trio()
    # Leaf 2 dies before voting: its channel EOFs at the hub.
    chans[3].close()
    out = _vote_all({"hub": hub, "l1": leaf1}, [True, True])
    assert out == {"hub": False, "l1": False}
    for c in chans:
        c.close()


# ------------------------------------------------- wiring + round artifacts


def test_wiring_document_shape(tmp_path):
    p = _pipeline()
    doc = write_wiring(
        tmp_path / "wiring_r0.json", round_no=0, pipeline=p,
        coordinator_ports=[50001, 50002],
        boundary_ports={0: {0: 50003, 1: 50004}},
        ctl_ports={0: 50005, 1: 50006},
    )
    on_disk = json.loads((tmp_path / "wiring_r0.json").read_text())
    assert on_disk == doc
    assert doc["version"] == 1 and doc["round"] == 0
    assert PipelineSpec.from_dict(doc["pipeline"]) == p
    (b,) = doc["boundaries"]
    assert b["from"] == 0 and b["to"] == 1
    assert sorted(b["listeners"]) == ["0", "1"]
    assert doc["ctl"]["0"]["port"] == 50005


def test_common_resume_step_is_the_cross_stage_intersection(tmp_path):
    def manifest(stage, step, proc, total):
        d = stage_ckpt_dir(tmp_path, stage) / f"step_{step}"
        d.mkdir(parents=True, exist_ok=True)
        (d / f"manifest_p{proc}.json").write_text(
            json.dumps({"num_processes": total})
        )

    assert common_resume_step(tmp_path, 2) == 0
    # Stage 0 has steps {5, 10}; stage 1 only {5}; stage 0's step 15 is
    # manifest-incomplete (1 of 2) and must not count.
    manifest(0, 5, 0, 2), manifest(0, 5, 1, 2)
    manifest(0, 10, 0, 2), manifest(0, 10, 1, 2)
    manifest(0, 15, 0, 2)
    manifest(1, 5, 0, 1)
    assert common_resume_step(tmp_path, 2) == 5
    manifest(1, 10, 0, 1)
    assert common_resume_step(tmp_path, 2) == 10


def test_read_drain_markers_tolerates_torn_writes(tmp_path):
    drain_marker_path(tmp_path, 0, 1).write_text(
        json.dumps({"step": 13, "why": "peer dead"})
    )
    drain_marker_path(tmp_path, 1, 0).write_text("{torn")
    out = read_drain_markers(tmp_path)
    assert out[(0, 1)]["step"] == 13
    assert out[(1, 0)] == {}  # torn, but the membership fact survives


# ---------------------------------------------------------- fixture replay


def test_fixture_replay_matches_committed_goldens():
    """Both committed fixtures replay byte-deterministically to their
    pinned CRCs — twice, to pin that nothing reads a clock."""
    from tpudml.mpmd.fixture import events_crc32, replay_fixture

    for name, rounds, worlds in [
        ("steady.json", 1, [2, 2]),
        ("shrink_stage.json", 2, [2, 1]),
    ]:
        a = replay_fixture(FIXTURES / name)
        b = replay_fixture(FIXTURES / name)
        assert a["ok"] and b["ok"], name
        assert a["lines"] == b["lines"], name
        assert a["events_crc32"] == a["expect_crc32"], name
        assert events_crc32(a["lines"]) == a["events_crc32"]
        assert a["rounds"] == rounds and a["final_stage_worlds"] == worlds


def test_fixture_replay_fresh_ports_per_reform():
    """Every re-form's coordinator/ctl ports are fresh — no port is
    ever reused across rounds (the controller's bind-and-hold contract,
    checkable in the simulated layout)."""
    from tpudml.mpmd.fixture import replay_fixture

    rep = replay_fixture(FIXTURES / "shrink_stage.json")
    forms = [json.loads(l) for l in rep["lines"]
             if json.loads(l).get("event") == "form"]
    assert len(forms) == 2
    ports = [
        p for f in forms
        for p in f["coordinator_ports"] + list(f["ctl_ports"].values())
    ]
    assert len(ports) == len(set(ports))
    assert forms[1]["resume_step"] == 2  # the pre-kill checkpoint
    assert forms[1]["stage_worlds"] == [2, 1]


def test_fixture_replay_quorum_halt():
    from tpudml.mpmd.fixture import replay_fixture

    fx = {
        "version": 1,
        "pipeline": _pipeline(
            stages=(
                StageSpec("trunk", dp=2, microbatches=2, min_world=2),
                StageSpec("head", dp=2),
            ),
        ).to_dict(),
        "engines": ["dp"],
        "events": [
            {"type": "step", "count": 1},
            {"type": "kill", "slot": 0},
            {"type": "step", "count": 5},  # unreachable past the halt
        ],
    }
    rep = replay_fixture(fx)
    assert rep["halted"] == "below_stage_quorum"
    assert rep["rounds"] == 1  # never re-formed
    assert json.loads(rep["lines"][-1]) == {
        "event": "halt", "reason": "below_stage_quorum",
    }


def test_fail_open_replan_on_vandalized_plan(tmp_path):
    """The PR 16 contract carried into MPMD: a vandalized plan file is
    never half-adopted, and a replanner that blows up mid-consult
    cannot stop the re-form — the replay records the error and the
    pipeline still shrinks in place."""
    from tpudml.elastic.replan import Replanner
    from tpudml.mpmd.fixture import replay_fixture
    from tpudml.resilience.faults import PLAN_VANDALS, vandalize_plan

    path = tmp_path / "plan.json"
    Replanner(engines=["dp", "zero1"], verify=False,
              plan_path=path).initial_plan(4)
    vandalize_plan(str(path), next(iter(PLAN_VANDALS)))
    assert Replanner(
        engines=["dp", "zero1"], verify=False
    ).load_existing(path) is None

    class _Boom:
        def initial_plan(self, world):
            return {"world": world}

        def replan(self, world, **kw):
            raise RuntimeError("boom")

    fx = json.loads((FIXTURES / "shrink_stage.json").read_text())
    fx.pop("expect")  # the golden pins the real planner's keys
    rep = replay_fixture(fx, replanner=_Boom())
    assert rep["halted"] is None and rep["rounds"] == 2
    (replan,) = [
        json.loads(l) for l in rep["lines"]
        if json.loads(l).get("event") == "replan"
    ]
    assert replan["error"] == "RuntimeError"
    assert replan["switched"] is False
    assert rep["final_stage_worlds"] == [2, 1]


def test_fixture_version_gate():
    from tpudml.mpmd.fixture import replay_fixture

    bad = json.loads((FIXTURES / "steady.json").read_text())
    bad["version"] = 9
    with pytest.raises(ValueError, match="fixture version"):
        replay_fixture(bad)


def test_fixture_cli_replays_without_spawning(tmp_path):
    """``python -m tpudml.mpmd --fixture ...`` is the meshless CI mode:
    one process, no gang, exit code is the replay verdict."""
    import subprocess

    proc = subprocess.run(
        [PY, "-m", "tpudml.mpmd",
         "--fixture", str(FIXTURES / "shrink_stage.json")],
        capture_output=True, text=True, timeout=120,
        env=dict(os.environ, JAX_PLATFORMS="cpu"),
    )
    assert proc.returncode == 0, proc.stderr
    report = json.loads(proc.stdout.splitlines()[-1])
    assert report["ok"] and report["rounds"] == 2
    assert "[replay]" in proc.stderr  # narration goes to stderr
    # A wrong golden flips the exit code — CI cannot rot silently.
    bad = json.loads((FIXTURES / "steady.json").read_text())
    bad["expect"]["events_crc32"] ^= 1
    bad_path = tmp_path / "bad.json"
    bad_path.write_text(json.dumps(bad))
    proc = subprocess.run(
        [PY, "-m", "tpudml.mpmd", "--fixture", str(bad_path)],
        capture_output=True, text=True, timeout=120,
        env=dict(os.environ, JAX_PLATFORMS="cpu"),
    )
    assert proc.returncode == 1
