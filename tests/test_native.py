"""C++ data-plane tests: native/fallback parity on every entry point.

The native library is the framework's answer to the reference's vendored
DataLoader internals (SURVEY.md §2.4 — C++ is the designated language for
host-side data speed). Every wrapper must be bit-identical to its numpy
fallback, and the u8-storage pipeline must produce the same batches as
float32 storage.
"""

import numpy as np
import pytest

from tpudml import native
from tpudml.data import DataLoader
from tpudml.data.datasets import ArrayDataset
from tpudml.data.idx import read_idx, write_idx


@pytest.fixture(scope="module")
def rng():
    return np.random.default_rng(7)


def test_native_library_builds():
    """g++ is in the image; the fast path must actually be active here."""
    assert native.available()


def _no_native(monkeypatch):
    monkeypatch.setattr(native, "_lib", None)
    monkeypatch.setattr(native, "_tried", True)


def test_gather_rows_f32_matches_numpy(rng, monkeypatch):
    src = rng.normal(size=(100, 7, 3)).astype(np.float32)
    idx = rng.integers(0, 100, size=33)
    fast = native.gather_rows(src, idx)
    _no_native(monkeypatch)
    slow = native.gather_rows(src, idx)
    np.testing.assert_array_equal(fast, slow)
    np.testing.assert_array_equal(fast, src[idx])


def test_gather_rows_u8_matches_numpy(rng):
    src = rng.integers(0, 255, size=(50, 4, 4, 1)).astype(np.uint8)
    idx = rng.integers(0, 50, size=16)
    np.testing.assert_array_equal(native.gather_rows(src, idx), src[idx])


def test_gather_normalize_matches_numpy(rng, monkeypatch):
    src = rng.integers(0, 255, size=(64, 28, 28, 1)).astype(np.uint8)
    idx = rng.integers(0, 64, size=20)
    fast = native.gather_normalize(src, idx, scale=1 / 255.0, bias=-0.5)
    assert fast.dtype == np.float32
    _no_native(monkeypatch)
    slow = native.gather_normalize(src, idx, scale=1 / 255.0, bias=-0.5)
    np.testing.assert_allclose(fast, slow, rtol=1e-6)


def test_gather_labels(rng):
    src = rng.integers(0, 10, size=500).astype(np.int32)
    idx = rng.integers(0, 500, size=77)
    np.testing.assert_array_equal(native.gather_labels(src, idx), src[idx])


@pytest.mark.parametrize("dtype", [np.int16, np.int32, np.float32, np.float64])
def test_byteswap_matches_numpy(rng, dtype):
    arr = (rng.normal(size=97) * 100).astype(dtype)
    want = arr.byteswap()
    got = native.byteswap_inplace(arr.copy())
    np.testing.assert_array_equal(got, want)


def test_byteswap_rejects_readonly(rng):
    arr = (rng.normal(size=8) * 10).astype(np.int32)
    arr.flags.writeable = False
    with pytest.raises(ValueError, match="writeable"):
        native.byteswap_inplace(arr)


def test_dataset_getitem_bool_and_slice(rng):
    raw = rng.integers(0, 255, size=(6, 2, 2, 1)).astype(np.uint8)
    ds = ArrayDataset(raw, np.arange(6, dtype=np.int32), scale=1 / 255.0)
    mask = np.array([True, False, False, False, True, False])
    imgs, lbls = ds[mask]
    np.testing.assert_array_equal(lbls, [0, 4])
    np.testing.assert_allclose(imgs, raw[[0, 4]].astype(np.float32) / 255.0)
    imgs, lbls = ds[1:3]
    np.testing.assert_array_equal(lbls, [1, 2])


def test_storage_validation(tmp_path):
    from tpudml.data.datasets import load_cifar10, load_dataset, load_mnist

    for fn in (load_mnist, load_cifar10):
        with pytest.raises(ValueError, match="storage"):
            fn(str(tmp_path), storage="uint8")
    with pytest.raises(ValueError, match="storage"):
        load_dataset("synthetic", str(tmp_path), "train", storage="U8")


def test_idx_multibyte_roundtrip(tmp_path):
    """int32/float IDX payloads exercise the native byteswap on read."""
    for arr in (
        np.arange(-50, 50, dtype=np.int32).reshape(10, 10),
        np.linspace(-1, 1, 24, dtype=np.float32).reshape(2, 3, 4),
    ):
        p = tmp_path / f"t-{arr.dtype}.idx"
        write_idx(p, arr)
        np.testing.assert_array_equal(read_idx(p), arr)


def test_out_of_range_index_raises(rng):
    """The C++ kernels do raw pointer math — bad indices must be rejected
    identically on both paths, never read out of bounds."""
    src = rng.normal(size=(10, 3)).astype(np.float32)
    with pytest.raises(IndexError):
        native.gather_rows(src, np.array([0, 10]))
    with pytest.raises(IndexError):
        native.gather_labels(np.zeros(10, np.int32), np.array([-11]))
    # Negative indices follow numpy semantics on both paths.
    np.testing.assert_array_equal(native.gather_rows(src, np.array([-1])), src[[-1]])


def test_getitem_matches_gather(rng):
    raw = rng.integers(0, 255, size=(10, 4, 4, 1)).astype(np.uint8)
    ds = ArrayDataset(raw, np.arange(10, dtype=np.int32), scale=1 / 255.0)
    img, lbl = ds[3]
    assert img.dtype == np.float32
    np.testing.assert_allclose(img, raw[3].astype(np.float32) / 255.0)
    assert lbl == 3
    imgs, lbls = ds[[1, 2]]
    assert imgs.shape == (2, 4, 4, 1) and imgs.dtype == np.float32


def test_u8_dataset_pipeline_matches_f32(rng):
    """End-to-end: a u8-storage dataset yields the same batches through the
    DataLoader as its float32-converted twin."""
    raw = rng.integers(0, 255, size=(40, 8, 8, 1)).astype(np.uint8)
    labels = rng.integers(0, 10, size=40).astype(np.int32)
    ds_u8 = ArrayDataset(raw, labels, scale=1 / 255.0)
    ds_f32 = ArrayDataset(raw.astype(np.float32) / 255.0, labels)
    batches_u8 = list(DataLoader(ds_u8, 8))
    batches_f32 = list(DataLoader(ds_f32, 8))
    assert len(batches_u8) == len(batches_f32) == 5
    for (xu, yu), (xf, yf) in zip(batches_u8, batches_f32):
        assert xu.dtype == np.float32
        np.testing.assert_allclose(xu, xf, rtol=1e-6)
        np.testing.assert_array_equal(yu, yf)
