"""bench.py protocol helpers (the driver-contract file).

The heavy bench entrypoints run on the chip; these pin the pure pieces:
MFU field construction with independent artifact flags per protocol,
peak lookup by device kind, and the fori timer's degenerate-measurement
fallback (never a garbage near-zero headline).
"""

import types

import jax
import jax.numpy as jnp

import bench


def test_mfu_fields_flags_each_protocol_independently():
    # fori physical, pipelined impossible -> only the pipelined flag trips.
    f = bench._mfu_fields(
        flops_per_step=1e12, sec_fori=0.01, sec_synced=0.02,
        sec_pipelined=1e-6, peak=200e12,
    )
    assert f["mfu"] == 0.5 and f["mfu_artifact"] is False
    assert f["mfu_pipelined"] > 1.0 and f["mfu_pipelined_artifact"] is True
    assert f["protocol"] == "fori"
    # No FLOPs -> timing fields only, no MFU claims.
    f2 = bench._mfu_fields(None, 0.01, 0.02, 0.03, 200e12)
    assert "mfu" not in f2 and "sec_per_step" in f2


def test_peak_flops_by_device_kind():
    dev = types.SimpleNamespace(device_kind="TPU v5 lite")
    assert bench._peak_flops(dev) == 197e12
    assert bench._peak_flops(types.SimpleNamespace(device_kind="TPU v4")) == 275e12
    assert bench._peak_flops(types.SimpleNamespace(device_kind="cpu")) is None


def test_time_fori_runs_and_is_positive():
    """Tiny body through the real fori timer (normal path)."""

    def body(ts, x, y):
        new = jax.tree.map(lambda a: a + 0.001 * x.sum(), ts)
        return new, jnp.sum(x) - jnp.sum(y)

    ts = {"w": jnp.ones((8, 8))}
    sec, runs = bench._time_fori(
        body, ts, (jnp.ones((4, 8)), jnp.ones((4, 8))), 2, 6, reps=3
    )
    assert sec > 0 and sec < 10
    assert len(runs) == 3 and sorted(runs)[1] == sec  # median of the reps


def test_time_fori_degenerate_fallback(monkeypatch):
    """Force t_hi <= t_lo with a scripted clock: the fallback must return
    the k_hi run INCLUDING overhead (an upper bound on sec/step), never a
    difference-derived garbage value (the near-zero-headline trap the
    round-2 review flagged)."""

    def body(ts, x, y):
        return ts, jnp.sum(x) - jnp.sum(y)

    # Each timed(k) consumes two perf_counter() reads (start, end).
    # Sequence: warm timed(2); t_lo = min of two timed(2) -> 5.0 each;
    # t_hi = min of two timed(6) -> 1.0 each. 1.0 <= 5.0 triggers the
    # fallback: sec = t_hi / k_hi = 1/6.
    deltas = iter([0.0, 0.1, 10.0, 15.0, 30.0, 35.0, 50.0, 51.0, 60.0, 61.0])
    monkeypatch.setattr(bench.time, "perf_counter", lambda: next(deltas))
    ts = {"w": jnp.ones((4, 4))}
    sec, runs = bench._time_fori(
        body, ts, (jnp.ones((2, 4)), jnp.ones((2, 4))), 2, 6, reps=1
    )
    assert abs(sec - 1.0 / 6) < 1e-9
    assert runs == [sec]


def test_analytic_lm_flops_mha_callers_may_omit_heads():
    """tools/ablate_lm.py passes only embed_dim/num_layers/vocab_size; the
    GQA extension must not make num_heads required (heads only matter when
    kv_heads differs), and the MHA count must be head-count independent."""
    base = dict(embed_dim=512, num_layers=6, vocab_size=32768)
    f_plain = bench._analytic_lm_flops(base, 8, 1024)
    f_mha = bench._analytic_lm_flops({**base, "num_heads": 4}, 8, 1024)
    assert f_plain == f_mha > 0
    f_gqa = bench._analytic_lm_flops(
        {**base, "num_heads": 4, "num_kv_heads": 1}, 8, 1024
    )
    assert f_gqa < f_mha  # GQA shrinks the k/v projections


def test_analytic_lm_flops_rejects_kv_heads_without_heads():
    import pytest

    with pytest.raises(ValueError, match="num_kv_heads"):
        bench._analytic_lm_flops(
            dict(embed_dim=512, num_layers=6, vocab_size=32768, num_kv_heads=2),
            8, 1024,
        )


def test_dryrun_sharded_fused_xent_regimes_compile():
    """The vocab-sharded fused-head regimes (task5 --parallel tp/fsdp
    --fused_xent) compile and run on the virtual CPU mesh — keeps the
    shard_map loss region + lse-merge collectives tracing without a
    chip."""
    import __graft_entry__

    __graft_entry__.dryrun_multichip(4, regimes=("tpfused", "fsdpfused"))


def test_ablate_budget_mode_runs_on_cpu():
    """The per-component budget mode (BASELINE.md round-6 table) at a
    tiny config: all five ablation arms patch/build/run and the table
    derives — so the one-process protocol is ready when chip time is."""
    from tools import ablate_lm

    total, comps = ablate_lm.budget(
        batch=2, seq_len=16, vocab=64, layers=1, dim=16, heads=2
    )
    assert total > 0
    assert set(comps) == {"attention", "junctions", "head", "embed", "adamw"}
