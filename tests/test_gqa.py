"""Grouped-query / multi-query attention tests.

Load-bearing properties: the K/V projections shrink to
num_kv_heads·head_dim, the math equals manually broadcasting each KV group
over its query heads, and GQA composes with the ring-CP and LM paths.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from tpudml.core.config import MeshConfig
from tpudml.core.dist import make_mesh
from tpudml.core.prng import seed_key
from tpudml.models import TransformerLM
from tpudml.nn import MultiHeadAttention
from tpudml.nn.attention import dot_product_attention
from tpudml.nn.losses import softmax_cross_entropy

B, T, D, H = 2, 16, 32, 4


@pytest.fixture(scope="module")
def x():
    return jnp.asarray(
        np.random.default_rng(0).normal(size=(B, T, D)).astype(np.float32)
    )


@pytest.mark.parametrize("kv_heads", [1, 2])
def test_gqa_matches_manual_broadcast(x, kv_heads):
    mha = MultiHeadAttention(D, H, causal=True, num_kv_heads=kv_heads)
    params, _ = mha.init(seed_key(0))
    hd = D // H
    assert params["k"]["kernel"].shape == (D, kv_heads * hd)
    assert params["v"]["kernel"].shape == (D, kv_heads * hd)
    got = mha(params, x)

    # Manual reference: project, reshape to kv heads, repeat per group.
    q = (x @ params["q"]["kernel"] + params["q"]["bias"]).reshape(B, T, H, hd)
    k = (x @ params["k"]["kernel"] + params["k"]["bias"]).reshape(B, T, kv_heads, hd)
    v = (x @ params["v"]["kernel"] + params["v"]["bias"]).reshape(B, T, kv_heads, hd)
    k = jnp.repeat(k, H // kv_heads, axis=2)
    v = jnp.repeat(v, H // kv_heads, axis=2)
    o = dot_product_attention(q, k, v, causal=True).reshape(B, T, D)
    want = o @ params["out"]["kernel"] + params["out"]["bias"]
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-6)


def test_invalid_kv_heads_rejected():
    with pytest.raises(ValueError, match="divide num_heads"):
        MultiHeadAttention(D, H, num_kv_heads=3)
    with pytest.raises(ValueError, match="divide num_heads"):
        MultiHeadAttention(D, H, num_kv_heads=0)


def test_gqa_ring_cp_matches_full(x):
    """GQA under ring context parallelism == GQA on one device."""
    from tpudml.optim import make_optimizer
    from tpudml.parallel.cp import ContextParallel

    mesh = make_mesh(MeshConfig({"seq": 4}), jax.devices()[:4])
    tokens = jnp.asarray(
        np.random.default_rng(1).integers(0, 32, size=(B, T)).astype(np.int32)
    )
    base = dict(vocab_size=32, embed_dim=D, num_heads=H, num_layers=1,
                max_len=T, num_kv_heads=2)
    params, _ = TransformerLM(**base).init(seed_key(2))
    want = TransformerLM(**base)(params, tokens)
    cp = ContextParallel(
        TransformerLM(**base, impl="ring", seq_sharded=True),
        make_optimizer("sgd", 0.1), mesh,
    )
    got = cp.make_forward()(params, tokens)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-4, atol=1e-5)


@pytest.mark.slow  # ~16s compile; GQA math + TP rules are each fast-covered
def test_gqa_composes_with_tensor_parallelism(x):
    """GQA under TP stays correct even when the shrunken K/V kernels can't
    shard head-aligned (apply_rules demotes them to replicated; GSPMD
    handles the resharding): trajectory matches single device."""
    from tpudml.optim import make_optimizer
    from tpudml.parallel.mp import GSPMDParallel, tensor_parallel_rules

    tokens = jnp.asarray(
        np.random.default_rng(5).integers(0, 32, size=(B, T + 1)).astype(np.int32)
    )
    xq, y = tokens[:, :-1], tokens[:, 1:]
    # embed_dim=24, H=4 → head_dim=6; MQA k/v kernels are [24, 6] and
    # 6 % 4 != 0, so apply_rules MUST demote them to replicated (the
    # documented GQA×TP fallback) while q stays head-sharded.
    base = dict(vocab_size=32, embed_dim=24, num_heads=H, num_layers=1,
                max_len=T, num_kv_heads=1)
    opt = make_optimizer("sgd", 0.1)
    mesh = make_mesh(MeshConfig({"model": 4}), jax.devices()[:4])
    tp = GSPMDParallel(
        TransformerLM(**base), opt, mesh,
        rule=tensor_parallel_rules("model"), axis_name="model",
    )
    ts = tp.create_state(seed_key(6))
    attn = ts.params["block0"]["attn"]
    # Demoted: no mesh axis on any dim (spelled P(None, None) by apply_rules).
    assert all(a is None for a in attn["k"]["kernel"].sharding.spec)
    assert attn["q"]["kernel"].sharding.spec == P(None, "model")
    ref_model = TransformerLM(**base)
    ref_params = jax.device_get(ts.params)
    ref_opt = opt.init(ref_params)
    ref_loss = lambda p: softmax_cross_entropy(ref_model(p, xq), y)
    step = tp.make_train_step()
    for _ in range(2):
        ts, _ = step(ts, xq, y)
        g = jax.grad(ref_loss)(ref_params)
        ref_params, ref_opt = opt.update(g, ref_opt, ref_params)
    for a, b in zip(jax.tree.leaves(ts.params), jax.tree.leaves(ref_params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-3, atol=2e-5)


def test_gqa_lm_trains(x):
    lm = TransformerLM(vocab_size=32, embed_dim=D, num_heads=H, num_layers=1,
                       max_len=T, num_kv_heads=1)
    params, _ = lm.init(seed_key(3))
    tokens = jnp.asarray(
        np.random.default_rng(2).integers(0, 32, size=(B, T + 1)).astype(np.int32)
    )
    loss = lambda p: softmax_cross_entropy(lm(p, tokens[:, :-1]), tokens[:, 1:])
    g = jax.grad(loss)(params)
    assert all(np.all(np.isfinite(np.asarray(leaf))) for leaf in jax.tree.leaves(g))
