"""tpudml.elastic: membership-aware restart + the scripted failure drill.

Controller semantics (policy, fresh rendezvous port, budget, min_world)
are pinned with jax-free subprocess children, so they run in seconds; the
full drill — real gloo collectives, SIGKILL-grade rank death, bit-exact
resume — is the e2e capstone and carries the multi-OS-process cost.
"""

import io
import sys

import pytest

from tpudml.elastic.controller import ROUND_ENV, ElasticController
from tpudml.launch.cluster import ClusterSpec

PY = sys.executable

# A child whose behaviour is scripted per (rank, round) via the
# controller's env contract — no jax import, so each round costs ~0.1s.
_SCRIPTED = """
import os, sys, time
rank = int(os.environ["TPUDML_PROCESS_ID"])
rnd = int(os.environ[{round_env!r}])
{body}
"""


def _child(body: str) -> list[str]:
    return [PY, "-c", _SCRIPTED.format(round_env=ROUND_ENV, body=body)]


def test_reform_fresh_port_and_no_zombie_deadlock():
    """Rank 1 dies in round 0 while rank 0 would block for 300s (the
    zombie): containment must kill rank 0 promptly, and the re-form must
    rendezvous on a port never used by round 0 — within a wall-clock
    budget nowhere near the zombie's sleep."""
    cmd = _child(
        "if rnd == 0:\n"
        "    if rank == 1:\n"
        "        sys.exit(3)\n"
        "    time.sleep(300)\n"
        "sys.exit(0)\n"
    )
    spec = ClusterSpec(num_processes=2, timeout_s=60.0, grace_s=1.0)
    res = ElasticController(cmd, spec, max_reforms=2, sink=io.StringIO()).run()
    assert res.success and res.stop_reason == "success"
    assert res.reforms == 1 and len(res.records) == 2
    assert res.records[0].failed_rank == 1
    assert res.records[0].returncodes[1] == 3
    assert res.records[1].coordinator_port != res.records[0].coordinator_port
    assert res.records[1].world == 2  # restart policy refills the slot
    assert res.total_elapsed_s < 30.0  # nobody waited for the zombie


def test_shrink_policy_reforms_at_world_minus_one():
    cmd = _child(
        "if rnd == 0 and rank == 2:\n"
        "    sys.exit(4)\n"
        "sys.exit(0)\n"
    )
    spec = ClusterSpec(num_processes=3, timeout_s=60.0, grace_s=1.0)
    res = ElasticController(
        cmd, spec, policy="shrink", max_reforms=2, sink=io.StringIO()
    ).run()
    assert res.success
    assert [r.world for r in res.records] == [3, 2]
    assert res.final_world == 2


def test_shrink_respects_min_world():
    cmd = _child("sys.exit(7)\n")
    spec = ClusterSpec(num_processes=2, timeout_s=60.0, grace_s=1.0)
    res = ElasticController(
        cmd, spec, policy="shrink", min_world=2, max_reforms=3,
        sink=io.StringIO(),
    ).run()
    assert not res.success
    assert res.stop_reason == "below_min_world"
    assert len(res.records) == 1  # no re-form below the quorum


def test_budget_is_charged_across_rounds_and_backoff():
    """A backoff that would overrun the whole-job budget must stop the
    controller instead of sleeping through it."""
    cmd = _child("sys.exit(5)\n")
    spec = ClusterSpec(
        num_processes=2,
        timeout_s=2.0,
        grace_s=0.5,
        restart_backoff_s=30.0,
    )
    res = ElasticController(cmd, spec, max_reforms=3, sink=io.StringIO()).run()
    assert not res.success
    assert res.stop_reason == "budget_exhausted"
    assert len(res.records) == 1
    assert res.total_elapsed_s < 5.0  # it did NOT take the 30s backoff


def test_max_reforms_bounds_rounds():
    cmd = _child("sys.exit(9)\n")
    spec = ClusterSpec(num_processes=2, timeout_s=60.0, grace_s=0.5)
    res = ElasticController(cmd, spec, max_reforms=2, sink=io.StringIO()).run()
    assert not res.success
    assert res.stop_reason == "max_reforms"
    assert len(res.records) == 3
    ports = [r.coordinator_port for r in res.records]
    assert len(set(ports)) == len(ports)  # every round rendezvoused fresh


def test_bad_policy_rejected():
    with pytest.raises(ValueError):
        ElasticController([PY, "-c", "pass"], policy="resurrect")


def test_drill_kill_reform_resume_bit_exact(tmp_path):
    """The tentpole e2e: 2-process gloo training, rank 1 hard-killed at
    step 13 → controller re-forms on a fresh port after seeded backoff →
    resume from the newest CRC-valid sharded checkpoint → final params
    bit-identical to an uninterrupted run, with one trace pid per rank."""
    from tpudml.elastic.drill import run_drill

    report = run_drill(str(tmp_path), timeout_s=300.0)
    assert report["ok"], report
    assert report["bit_exact"]
    assert report["reforms"] == 1
    assert report["killed_rank_observed"] == 1
    assert report["resume_step"] == 10  # newest checkpoint before step 13
    assert report["steps_lost"] == 3
    assert report["fresh_port"]
    assert report["backoff_s"] > 0
    assert report["restart_latency_s"] > report["backoff_s"]
    assert report["trace_pids"] == [0, 1]
    merged = tmp_path / "obs" / "trace.json"
    assert merged.exists()
