"""tpudml.elastic: membership-aware restart + adaptive re-plan + drills.

Controller semantics (policy, fresh rendezvous port, budget, min_world,
re-plan consultation) are pinned with jax-free subprocess children and
stub replanners, so they run in seconds; fixture replay exercises the
real planner meshlessly. The shrink-re-plan drill — real gloo
collectives, SIGKILL-grade rank death, planner-driven engine-chain
switch, bit-exact resume — is the e2e capstone and the one test here
that carries the multi-OS-process cost tier-1 (the PR 14 restart drill
is demoted to the slow tier: the shrink drill supersedes its coverage).
"""

import io
import json
import os
import sys
from pathlib import Path

import pytest

from tpudml.elastic.controller import ROUND_ENV, ElasticController
from tpudml.launch.cluster import ClusterSpec

FIXTURES = Path(__file__).parent / "elastic_fixtures"

PY = sys.executable

# A child whose behaviour is scripted per (rank, round) via the
# controller's env contract — no jax import, so each round costs ~0.1s.
_SCRIPTED = """
import os, sys, time
rank = int(os.environ["TPUDML_PROCESS_ID"])
rnd = int(os.environ[{round_env!r}])
{body}
"""


def _child(body: str) -> list[str]:
    return [PY, "-c", _SCRIPTED.format(round_env=ROUND_ENV, body=body)]


def test_reform_fresh_port_and_no_zombie_deadlock():
    """Rank 1 dies in round 0 while rank 0 would block for 300s (the
    zombie): containment must kill rank 0 promptly, and the re-form must
    rendezvous on a port never used by round 0 — within a wall-clock
    budget nowhere near the zombie's sleep."""
    cmd = _child(
        "if rnd == 0:\n"
        "    if rank == 1:\n"
        "        sys.exit(3)\n"
        "    time.sleep(300)\n"
        "sys.exit(0)\n"
    )
    spec = ClusterSpec(num_processes=2, timeout_s=60.0, grace_s=1.0)
    res = ElasticController(cmd, spec, max_reforms=2, sink=io.StringIO()).run()
    assert res.success and res.stop_reason == "success"
    assert res.reforms == 1 and len(res.records) == 2
    assert res.records[0].failed_rank == 1
    assert res.records[0].returncodes[1] == 3
    assert res.records[1].coordinator_port != res.records[0].coordinator_port
    assert res.records[1].world == 2  # restart policy refills the slot
    assert res.total_elapsed_s < 30.0  # nobody waited for the zombie


def test_shrink_policy_reforms_at_world_minus_one():
    cmd = _child(
        "if rnd == 0 and rank == 2:\n"
        "    sys.exit(4)\n"
        "sys.exit(0)\n"
    )
    spec = ClusterSpec(num_processes=3, timeout_s=60.0, grace_s=1.0)
    res = ElasticController(
        cmd, spec, policy="shrink", max_reforms=2, sink=io.StringIO()
    ).run()
    assert res.success
    assert [r.world for r in res.records] == [3, 2]
    assert res.final_world == 2


def test_shrink_respects_min_world():
    cmd = _child("sys.exit(7)\n")
    spec = ClusterSpec(num_processes=2, timeout_s=60.0, grace_s=1.0)
    res = ElasticController(
        cmd, spec, policy="shrink", min_world=2, max_reforms=3,
        sink=io.StringIO(),
    ).run()
    assert not res.success
    assert res.stop_reason == "below_min_world"
    assert len(res.records) == 1  # no re-form below the quorum


def test_budget_is_charged_across_rounds_and_backoff():
    """A backoff that would overrun the whole-job budget must stop the
    controller instead of sleeping through it."""
    cmd = _child("sys.exit(5)\n")
    spec = ClusterSpec(
        num_processes=2,
        timeout_s=2.0,
        grace_s=0.5,
        restart_backoff_s=30.0,
    )
    res = ElasticController(cmd, spec, max_reforms=3, sink=io.StringIO()).run()
    assert not res.success
    assert res.stop_reason == "budget_exhausted"
    assert len(res.records) == 1
    assert res.total_elapsed_s < 5.0  # it did NOT take the 30s backoff


def test_max_reforms_bounds_rounds():
    cmd = _child("sys.exit(9)\n")
    spec = ClusterSpec(num_processes=2, timeout_s=60.0, grace_s=0.5)
    res = ElasticController(cmd, spec, max_reforms=2, sink=io.StringIO()).run()
    assert not res.success
    assert res.stop_reason == "max_reforms"
    assert len(res.records) == 3
    ports = [r.coordinator_port for r in res.records]
    assert len(set(ports)) == len(ports)  # every round rendezvoused fresh


def test_bad_policy_rejected():
    with pytest.raises(ValueError):
        ElasticController([PY, "-c", "pass"], policy="resurrect")


# ------------------------------------------------------- port reservation


def test_fresh_port_reservation_is_bind_and_hold():
    """The fresh-port path must HOLD the socket it picked (not
    bind-close-return, which races any other process grabbing ephemeral
    ports between the close and the child's bind)."""
    from tpudml.resilience.faults import occupy_port

    ctrl = ElasticController([PY, "-c", "pass"], ClusterSpec(num_processes=1))
    sock, port = ctrl._reserve_fresh_port(set())
    try:
        with pytest.raises(OSError):  # held: nobody else can take it
            occupy_port(port)
    finally:
        sock.close()
    occupy_port(port).close()  # released: bindable again


def test_pinned_port_collision_falls_back_to_fresh_port():
    """Regression for the coordinator-port race: a squatter on the
    pinned port must push the controller to a fresh port, not a
    crash-loop of bind failures."""
    from tpudml.resilience.faults import occupy_port

    squat = occupy_port(0)
    try:
        port = squat.getsockname()[1]
        sink = io.StringIO()
        res = ElasticController(
            _child("sys.exit(0)\n"),
            ClusterSpec(num_processes=2, coordinator_port=port,
                        timeout_s=60.0, grace_s=1.0),
            sink=sink,
        ).run()
        assert res.success
        assert res.records[0].coordinator_port != port
        assert "falling back to a fresh port" in sink.getvalue()
    finally:
        squat.close()


# ---------------------------------------------------- re-plan consultation


class _StubReplanner:
    """Records consultations; returns a plain-dict decision (the
    controller accepts any object with .to_dict() or dict(...))."""

    def __init__(self):
        self.calls = []

    def replan(self, world, *, why="membership change", trigger="membership"):
        self.calls.append((world, why))
        return {
            "trigger": trigger, "why": why,
            "old_world": world + 1, "new_world": world,
            "old_key": "zero1[data=2]", "new_key": "dp[data=1]",
            "switched": True, "latency_s": 0.01,
            "receipts": [{"verdict": "infeasible_at_world"}],
            "calibration": None, "error": None,
        }


class _ExplodingReplanner:
    def replan(self, world, **_):
        raise RuntimeError("boom")


def test_shrink_consults_replanner_and_records_decision():
    cmd = _child(
        "if rnd == 0 and rank == 1:\n"
        "    sys.exit(4)\n"
        "sys.exit(0)\n"
    )
    rp = _StubReplanner()
    sink = io.StringIO()
    res = ElasticController(
        cmd, ClusterSpec(num_processes=2, timeout_s=60.0, grace_s=1.0),
        policy="shrink", min_world=1, max_reforms=2, replanner=rp, sink=sink,
    ).run()
    assert res.success
    assert [w for w, _ in rp.calls] == [1]
    assert "rank 1" in rp.calls[0][1]  # the membership why reaches the planner
    assert len(res.replans) == 1
    rep = res.replans[0]
    assert rep["round"] == 1 and rep["new_world"] == 1
    assert rep["switched"] and rep["error"] is None
    assert rep["receipts"][0]["verdict"] == "infeasible_at_world"
    assert "engine chain switched" in sink.getvalue()
    assert res.to_dict()["replans"] == res.replans


def test_restart_policy_does_not_consult_replanner():
    """World unchanged → no membership change → no re-plan."""
    cmd = _child(
        "if rnd == 0 and rank == 1:\n"
        "    sys.exit(4)\n"
        "sys.exit(0)\n"
    )
    rp = _StubReplanner()
    res = ElasticController(
        cmd, ClusterSpec(num_processes=2, timeout_s=60.0, grace_s=1.0),
        policy="restart", max_reforms=2, replanner=rp, sink=io.StringIO(),
    ).run()
    assert res.success and res.reforms == 1
    assert rp.calls == [] and res.replans == []


def test_replanner_failure_does_not_kill_recovery():
    """Fail-open: a planner crash during recovery is recorded and the
    re-form proceeds under the old plan."""
    cmd = _child(
        "if rnd == 0 and rank == 1:\n"
        "    sys.exit(4)\n"
        "sys.exit(0)\n"
    )
    sink = io.StringIO()
    res = ElasticController(
        cmd, ClusterSpec(num_processes=2, timeout_s=60.0, grace_s=1.0),
        policy="shrink", min_world=1, max_reforms=2,
        replanner=_ExplodingReplanner(), sink=sink,
    ).run()
    assert res.success and res.reforms == 1
    assert len(res.replans) == 1
    assert "RuntimeError: boom" in res.replans[0]["error"]
    assert "keeping the old plan" in sink.getvalue()


def test_reform_survives_straggler_rejoiner():
    """A rank that stalls while rejoining the re-formed gang delays but
    does not break the round (the launcher waits the gang out)."""
    cmd = _child(
        "if rnd == 0 and rank == 1:\n"
        "    sys.exit(3)\n"
        "if rnd == 1 and rank == 0:\n"
        "    time.sleep(1.5)\n"
        "sys.exit(0)\n"
    )
    res = ElasticController(
        cmd, ClusterSpec(num_processes=2, timeout_s=60.0, grace_s=1.0),
        max_reforms=2, sink=io.StringIO(),
    ).run()
    assert res.success and res.reforms == 1
    assert res.records[1].elapsed_s >= 1.0  # the straggle was real


def test_reform_straggler_hook_gates_on_round_and_rank(monkeypatch):
    from tpudml.resilience import faults

    slept = []
    monkeypatch.setattr(faults.time, "sleep", lambda s: slept.append(s))
    monkeypatch.setenv("TPUDML_PROCESS_ID", "1")
    monkeypatch.setenv("TPUDML_ELASTIC_ROUND", "0")
    hook = faults.reform_straggler_hook(2.0, round=1, rank=1)
    hook(step=0)
    assert slept == []  # wrong round
    monkeypatch.setenv("TPUDML_ELASTIC_ROUND", "1")
    monkeypatch.setenv("TPUDML_PROCESS_ID", "0")
    hook(step=0)
    assert slept == []  # wrong rank
    monkeypatch.setenv("TPUDML_PROCESS_ID", "1")
    hook(step=0)
    hook(step=1)
    assert slept == [2.0]  # fired exactly once


# -------------------------------------------------- replanner + plan file


def test_real_replanner_fails_open_when_no_candidate_fits():
    """zero1-only lattice has no mesh at world 1: the re-plan records
    the error and keeps the old plan instead of raising mid-recovery."""
    from tpudml.elastic.replan import Replanner

    rp = Replanner(engines=["zero1"], verify=False)
    rp.initial_plan(2)
    old_key = rp.winner_key
    rec = rp.replan(1, why="shrink to 1")
    assert rec.error is not None
    assert rp.winner_key == old_key  # plan unchanged
    assert rp.plan["world"] == 2


def test_vandalized_plan_degrades_to_replan_from_scratch(tmp_path):
    """Every plan vandal (torn write, garbage bytes, bad version) must
    make load_existing return None — never half-adopt a broken plan —
    and a fresh plan from scratch must still come out."""
    from tpudml.elastic.replan import Replanner
    from tpudml.resilience.faults import PLAN_VANDALS, vandalize_plan

    for kind in PLAN_VANDALS:
        path = tmp_path / f"{kind}.json"
        Replanner(engines=["dp", "zero1"], verify=False,
                  plan_path=path).initial_plan(2)
        vandalize_plan(str(path), kind)
        rp = Replanner(engines=["dp", "zero1"], verify=False)
        assert rp.load_existing(path) is None, kind
        assert rp.plan is None
        assert rp.initial_plan(2)["winner"]["candidate"]["key"]

    # Control: an intact plan file IS adopted.
    path = tmp_path / "intact.json"
    Replanner(engines=["dp", "zero1"], verify=False,
              plan_path=path).initial_plan(2)
    rp = Replanner(engines=["dp", "zero1"], verify=False)
    assert rp.load_existing(path)["world"] == 2


# ---------------------------------------------------------- fixture replay


def test_fixture_replay_drift_fires_and_calibrates():
    """The committed shrink+drift fixture: membership re-plans produce
    receipts, the >10% drift event fires and folds the measured
    constants into the plan's calibration block."""
    from tpudml.elastic.replan import replay_fixture

    rep = replay_fixture(FIXTURES / "shrink_then_drift.json")
    assert rep["ok"]
    assert rep["events"] == 3
    assert rep["drift_checks"] == 1 and rep["drift_firings"] == 1
    assert rep["plan_switches"] == 2  # 4→2 re-mesh, 2→1 chain switch
    drift_recs = [r for r in rep["replans"] if r["trigger"] == "drift"]
    assert len(drift_recs) == 1
    assert drift_recs[0]["calibration"]["comm_scale"] == pytest.approx(1.25)
    assert rep["final"]["calibration"]["comm_scale"] == pytest.approx(1.25)
    verdicts = [c["verdict"] for r in rep["replans"] for c in r["receipts"]]
    assert "infeasible_at_world" in verdicts  # zero1 at world 1
    assert "retained" in verdicts  # zero1 at world 2
    assert rep["final"]["engine_config"]["engine"] == "dp"


def test_fixture_replay_fresh_report_does_not_replan():
    """In-threshold drift → no re-plan, no calibration: the runtime
    trigger has no false positives."""
    from tpudml.elastic.replan import replay_fixture

    rep = replay_fixture(FIXTURES / "fresh.json")
    assert rep["ok"]
    assert rep["drift_checks"] == 1 and rep["drift_firings"] == 0
    assert rep["replans"] == [] and rep["plan_switches"] == 0
    assert rep["final"]["calibration"] is None
    assert rep["final"]["winner"] == rep["initial"]["winner"]


def test_fixture_cli_replays_without_spawning(tmp_path):
    """``python -m tpudml.elastic --drill --fixture ...`` is the
    meshless CI mode: one process, no gang spawned, exit code is the
    replay verdict."""
    import subprocess

    proc = subprocess.run(
        [PY, "-m", "tpudml.elastic", "--drill",
         "--fixture", str(FIXTURES / "shrink_then_drift.json")],
        capture_output=True, text=True, timeout=120,
        env=dict(os.environ, JAX_PLATFORMS="cpu"),
    )
    assert proc.returncode == 0, proc.stderr
    report = json.loads(proc.stdout.splitlines()[-1])
    assert report["ok"] and report["drift_firings"] == 1
    assert "[replay]" in proc.stderr  # narration goes to stderr


def test_fixture_version_gate(tmp_path):
    from tpudml.elastic.replan import replay_fixture

    bad = dict(json.loads((FIXTURES / "fresh.json").read_text()), version=7)
    with pytest.raises(ValueError, match="fixture version"):
        replay_fixture(bad)


# -------------------------------------------------------------- e2e drills


def test_shrink_replan_drill_e2e(tmp_path):
    """The PR 16 tentpole e2e: 2-process ZeRO-1+accum training (chain
    chosen by the planner via --plan), rank 1 hard-killed at step 13 →
    shrink to world 1 → planner consulted (ZeRO-1 infeasible on one
    chip, receipts say so) → resume from the CRC-valid sharded
    checkpoint under plain DP → final params AND loss history bit-exact
    vs an uninterrupted world-1 DP run from the same checkpoint."""
    from tpudml.elastic.drill import run_shrink_drill

    report = run_shrink_drill(str(tmp_path), timeout_s=300.0)
    assert report["ok"], report
    assert report["bit_exact"]
    assert report["reforms"] == 1 and report["final_world"] == 1
    assert report["killed_rank_observed"] == 1
    assert report["old_plan"]["engine"] == "zero1"
    assert report["old_plan"]["accum_steps"] == 2
    assert report["new_plan"]["engine"] == "dp"
    assert report["plan_switched"] and report["chain_switched"]
    assert report["resume_step"] == 10 and report["steps_lost"] == 3
    assert [r["verdict"] for r in report["replan_receipts"]] == [
        "infeasible_at_world"
    ]
    assert report["fresh_port"]
    assert report["replan_latency_s"] is not None
    assert report["post_shrink_steps_per_s"] > 0
    # The artifacts the obs report reads.
    assert (tmp_path / "obs" / "elastic.json").exists()
    assert (tmp_path / "obs" / "trace_controller.json").exists()
    # plan.json on disk is the re-planned v2 plan the continuation ran
    # under, provenance block included.
    plan = json.loads((tmp_path / "plan.json").read_text())
    assert plan["version"] == 2
    assert plan["world"] == 1
    assert plan["engine_config"]["engine"] == "dp"
    assert plan["replan"]["trigger"] == "membership"
    assert plan["replan"]["old_winner"]["engine"] == "zero1"


@pytest.mark.slow
def test_drill_kill_reform_resume_bit_exact(tmp_path):
    """The tentpole e2e: 2-process gloo training, rank 1 hard-killed at
    step 13 → controller re-forms on a fresh port after seeded backoff →
    resume from the newest CRC-valid sharded checkpoint → final params
    bit-identical to an uninterrupted run, with one trace pid per rank."""
    from tpudml.elastic.drill import run_drill

    report = run_drill(str(tmp_path), timeout_s=300.0)
    assert report["ok"], report
    assert report["bit_exact"]
    assert report["reforms"] == 1
    assert report["killed_rank_observed"] == 1
    assert report["resume_step"] == 10  # newest checkpoint before step 13
    assert report["steps_lost"] == 3
    assert report["fresh_port"]
    assert report["backoff_s"] > 0
    assert report["restart_latency_s"] > report["backoff_s"]
    assert report["trace_pids"] == [0, 1]
    merged = tmp_path / "obs" / "trace.json"
    assert merged.exists()
