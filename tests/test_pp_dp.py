"""PP×DP composition: pipeline engines on a 2-D {data, stage} mesh.

Parity contract (VERDICT r2 item 3): sharding the global batch over a
``data`` axis while pipelining over ``stage`` must reproduce the
single-device sequential math exactly — same logits, same first update,
same trajectory — at matched global batch. Mirrors the CP×DP / TP×DP
composition tests.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpudml.core.config import MeshConfig
from tpudml.core.dist import make_mesh, shard_index_key
from tpudml.core.prng import seed_key
from tpudml.nn import Activation, Dense, Sequential
from tpudml.nn.losses import softmax_cross_entropy
from tpudml.optim import make_optimizer
from tpudml.parallel.pp import GPipe, OneFOneB

STAGES = 4
DATA = 2
WIDTH = 32
BATCH = 16  # global; 8 rows per data replica


def make_mesh2d():
    return make_mesh(
        MeshConfig({"data": DATA, "stage": STAGES}), jax.devices()[: DATA * STAGES]
    )


def make_pipe(cls=GPipe, n_microbatches=4, opt=None, **kw):
    block = Sequential((Dense(WIDTH, WIDTH), Activation(jax.nn.relu)))
    return cls(
        block,
        n_microbatches=n_microbatches,
        mesh=make_mesh2d(),
        optimizer=opt or make_optimizer("sgd", 0.05, momentum=0.9),
        prologue=Dense(16, WIDTH),
        epilogue=Dense(WIDTH, 10),
        batch_axis="data",
        **kw,
    )


@pytest.fixture(scope="module")
def batch():
    rng = np.random.default_rng(7)
    x = rng.normal(size=(BATCH, 16)).astype(np.float32)
    y = rng.integers(0, 10, size=(BATCH,)).astype(np.int32)
    return jnp.asarray(x), jnp.asarray(y)


def test_forward_matches_sequential(batch):
    x, _ = batch
    pipe = make_pipe()
    params = pipe.init_params(seed_key(0))
    got = pipe.make_forward()(params, x)
    want = pipe.sequential_forward(params, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-6)


@pytest.mark.parametrize("cls", [GPipe, OneFOneB])
def test_train_step_matches_single_device(batch, cls):
    """4 stage × 2 data replicas, global batch 16: first update must equal
    the single-device update on the full batch."""
    x, y = batch
    opt = make_optimizer("sgd", 0.05, momentum=0.9)
    pipe = make_pipe(cls, opt=opt)
    ts = pipe.create_state(seed_key(1))
    params0 = jax.device_get(ts.params)

    new_ts, metrics = pipe.make_train_step()(ts, x, y)

    ref_loss = lambda p: softmax_cross_entropy(pipe.sequential_forward(p, x), y)
    loss0, ref_grads = jax.value_and_grad(ref_loss)(params0)
    ref_params, _ = opt.update(ref_grads, opt.init(params0), params0)

    np.testing.assert_allclose(float(metrics["loss"]), float(loss0), rtol=1e-5)
    for a, b in zip(jax.tree.leaves(new_ts.params), jax.tree.leaves(ref_params)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-4, atol=1e-6
        )


def test_trajectory_descends_and_replicas_stay_synced(batch):
    x, y = batch
    pipe = make_pipe(n_microbatches=2)
    ts = pipe.create_state(seed_key(2))
    step = pipe.make_train_step()
    losses = []
    for _ in range(5):
        ts, m = step(ts, x, y)
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0]
    # Params carry P("stage") shardings on the 2-D mesh: every data
    # replica must hold bitwise-identical stage slices. addressable_shards
    # groups by device; compare replicas of the same stage slice.
    leaf = jax.tree.leaves(ts.params["stages"])[0]
    shard_by_stage = {}
    for s in leaf.addressable_shards:
        # Shard.index is a tuple of slices — unhashable before py3.12.
        key = shard_index_key(s.index)
        got = np.asarray(s.data)
        if key in shard_by_stage:
            np.testing.assert_array_equal(shard_by_stage[key], got)
        else:
            shard_by_stage[key] = got


def test_bad_batch_axis_rejected():
    block = Sequential((Dense(WIDTH, WIDTH),))
    with pytest.raises(ValueError, match="batch_axis"):
        GPipe(
            block, n_microbatches=2, mesh=make_mesh2d(),
            optimizer=make_optimizer("sgd", 0.1), batch_axis="nope",
        )
