"""Unit tests: IDX codec, datasets, samplers (partition disjointness /
coverage, per-epoch reshuffle, sampling-mode overlap), loaders."""

import numpy as np
import pytest

from tpudml.data import (
    DataLoader,
    RandomPartitionSampler,
    RandomSamplingSampler,
    SequentialSampler,
    load_dataset,
    make_sampler,
    read_idx,
    write_idx,
)
from tpudml.data.datasets import ArrayDataset, synthetic_classification
from tpudml.data.loader import ShardedDataLoader


def test_idx_roundtrip(tmp_path):
    for dtype in (np.uint8, np.int32, np.float32):
        arr = (np.arange(2 * 3 * 4).reshape(2, 3, 4) % 200).astype(dtype)
        p = tmp_path / f"x-{np.dtype(dtype).name}.idx"
        write_idx(p, arr)
        out = read_idx(p)
        np.testing.assert_array_equal(out, arr)
        assert out.dtype == dtype


def test_idx_gzip_roundtrip(tmp_path):
    arr = np.random.default_rng(0).integers(0, 255, (10, 28, 28)).astype(np.uint8)
    p = tmp_path / "imgs.idx.gz"
    write_idx(p, arr)
    np.testing.assert_array_equal(read_idx(p), arr)


def test_mnist_idx_loading(tmp_path):
    """Write IDX files in the torchvision layout and load them through the
    mnist loader (no synthetic fallback)."""
    raw = tmp_path / "MNIST" / "raw"
    raw.mkdir(parents=True)
    imgs = np.random.default_rng(0).integers(0, 255, (50, 28, 28)).astype(np.uint8)
    labels = (np.arange(50) % 10).astype(np.uint8)
    write_idx(raw / "train-images-idx3-ubyte", imgs)
    write_idx(raw / "train-labels-idx1-ubyte", labels)
    ds = load_dataset("mnist", str(tmp_path), "train", synthetic_fallback=False)
    assert ds.images.shape == (50, 28, 28, 1)
    # Default storage keeps raw bytes; normalization is fused into gather.
    assert ds.images.dtype == np.uint8
    batch, lbls = ds.gather(np.arange(50))
    assert batch.dtype == np.float32
    assert batch.max() <= 1.0
    np.testing.assert_array_equal(lbls, labels)
    np.testing.assert_array_equal(ds.labels, labels)

    f32 = load_dataset(
        "mnist", str(tmp_path), "train", synthetic_fallback=False, storage="f32"
    )
    assert f32.images.dtype == np.float32
    np.testing.assert_allclose(batch, f32.images, rtol=1e-6)


def test_synthetic_fallback_deterministic():
    a = load_dataset("mnist", "/nonexistent", "train", synthetic_size=100)
    b = load_dataset("mnist", "/nonexistent", "train", synthetic_size=100)
    np.testing.assert_array_equal(a.images, b.images)
    assert a.images.shape == (100, 28, 28, 1)


def test_synthetic_is_learnable():
    """Nearest-prototype classification must beat chance by a wide margin —
    guarantees accuracy assertions in integration tests are meaningful."""
    imgs, labels = synthetic_classification(500, (8, 8, 1), 10, seed=0)
    protos = np.stack([imgs[labels == c].mean(0) for c in range(10)])
    pred = np.argmin(
        ((imgs[:, None] - protos[None]) ** 2).sum((2, 3, 4)), axis=1
    )
    assert (pred == labels).mean() > 0.9


def test_partition_disjoint_and_exhaustive():
    """Random-partition mode: shards are disjoint and cover the dataset
    (sections/checking.tex:13)."""
    n, world = 103, 4
    samplers = [
        RandomPartitionSampler(n, world, r, shuffle=True, seed=7) for r in range(world)
    ]
    shards = [set(s._indices().tolist()) for s in samplers]
    union = set().union(*shards)
    assert union == set(range(n))
    # padding wraps a few indices; all NON-padded entries must be disjoint
    total = sum(len(s) for s in shards)
    assert total == -(-n // world) * world
    overlap = sum(
        len(a & b) for i, a in enumerate(shards) for b in shards[i + 1 :]
    )
    assert overlap <= total - n  # only the wrap-padding may repeat


def test_partition_reshuffles_per_epoch():
    s = RandomPartitionSampler(100, 2, 0, shuffle=True, seed=0)
    e0 = s._indices().copy()
    s.set_epoch(1)
    e1 = s._indices()
    assert not np.array_equal(e0, e1)
    s.set_epoch(0)
    np.testing.assert_array_equal(s._indices(), e0)


def test_sampling_mode_overlaps_across_ranks():
    """Random-sampling mode: per-rank independent draws overlap with high
    probability and differ between ranks."""
    n, world = 1000, 4
    samplers = [
        RandomSamplingSampler(n, world, r, shuffle=True, seed=0) for r in range(world)
    ]
    shards = [set(s._indices().tolist()) for s in samplers]
    assert shards[0] != shards[1]
    overlap = len(shards[0] & shards[1])
    assert overlap > 0  # birthday bound: 250 draws from 1000 twice → overlap ~62


def test_sampler_len_is_ceil():
    s = RandomPartitionSampler(10, 3, 0)
    assert len(s) == 4
    assert len(list(iter(s))) == 4


def test_make_sampler_factory():
    assert isinstance(make_sampler("partition", 10, 2, 0), RandomPartitionSampler)
    assert isinstance(make_sampler("sampling", 10, 2, 0), RandomSamplingSampler)
    assert isinstance(make_sampler("sequential", 10, 2, 1), SequentialSampler)
    with pytest.raises(ValueError):
        make_sampler("bogus", 10, 2, 0)
    with pytest.raises(ValueError):
        make_sampler("partition", 10, 2, 5)


def test_dataloader_batching():
    imgs = np.arange(10, dtype=np.float32).reshape(10, 1, 1, 1)
    ds = ArrayDataset(imgs, np.arange(10, dtype=np.int32))
    ld = DataLoader(ds, batch_size=3, drop_remainder=True)
    batches = list(ld)
    assert len(batches) == 3 == len(ld)
    assert all(b[0].shape == (3, 1, 1, 1) for b in batches)
    ld2 = DataLoader(ds, batch_size=3, drop_remainder=False)
    assert len(list(ld2)) == 4


def test_sharded_loader_stacks_replicas():
    imgs = np.arange(24, dtype=np.float32).reshape(24, 1, 1, 1)
    ds = ArrayDataset(imgs, np.arange(24, dtype=np.int32))
    samplers = [RandomPartitionSampler(24, 4, r, seed=3) for r in range(4)]
    ld = ShardedDataLoader(ds, batch_size=2, samplers=samplers)
    x, y = next(iter(ld))
    assert x.shape == (4, 2, 1, 1, 1)
    assert y.shape == (4, 2)
    # per-replica streams are disjoint within the step
    assert len(np.unique(y)) == 8
