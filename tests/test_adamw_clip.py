"""AdamW (decoupled weight decay) + global-norm gradient clipping tests."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpudml.optim import Adam, AdamW, ClipByGlobalNorm, Scheduled, Sgd, constant
from tpudml.optim import make_optimizer


def test_adamw_decouples_decay():
    """AdamW == Adam followed by -lr·wd·p on the ORIGINAL params (the
    decay never touches the moments)."""
    params = {"w": jnp.asarray([1.0, -2.0, 3.0])}
    grads = {"w": jnp.asarray([0.5, 0.5, -0.5])}
    lr, wd = 0.1, 0.04
    adam, adamw = Adam(lr=lr), AdamW(lr=lr, weight_decay=wd)
    pa, sa = adam.update(grads, adam.init(params), params)
    pw, sw = adamw.update(grads, adamw.init(params), params)
    np.testing.assert_allclose(
        np.asarray(pw["w"]), np.asarray(pa["w"]) - lr * wd * np.asarray(params["w"]),
        rtol=1e-6,
    )
    # Moments identical: decay is decoupled.
    for a, b in zip(jax.tree.leaves(sa), jax.tree.leaves(sw)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_adamw_zero_decay_is_adam():
    params = {"w": jnp.arange(4.0)}
    grads = {"w": jnp.ones(4)}
    pa, _ = Adam(lr=0.1).update(grads, Adam(lr=0.1).init(params), params)
    pw, _ = AdamW(lr=0.1, weight_decay=0.0).update(
        grads, AdamW(lr=0.1).init(params), params
    )
    np.testing.assert_array_equal(np.asarray(pa["w"]), np.asarray(pw["w"]))


def test_clip_rescales_only_above_threshold():
    params = {"a": jnp.zeros(3), "b": jnp.zeros(2)}
    opt = ClipByGlobalNorm(Sgd(lr=1.0), max_norm=1.0)
    state = opt.init(params)

    small = {"a": jnp.asarray([0.1, 0.2, 0.2]), "b": jnp.asarray([0.1, 0.1])}
    p1, _ = opt.update(small, state, params)
    np.testing.assert_allclose(  # untouched below the threshold
        np.asarray(p1["a"]), -np.asarray(small["a"]), rtol=1e-6
    )

    big = {"a": jnp.asarray([3.0, 0.0, 0.0]), "b": jnp.asarray([0.0, 4.0])}
    p2, _ = opt.update(big, state, params)
    flat = np.concatenate([np.asarray(-p2["a"]), np.asarray(-p2["b"])])
    np.testing.assert_allclose(np.linalg.norm(flat), 1.0, rtol=1e-6)  # norm 5 → 1
    np.testing.assert_allclose(flat, np.asarray([0.6, 0, 0, 0, 0.8]), rtol=1e-6)


def test_clip_composes_with_scheduled():
    opt = ClipByGlobalNorm(Scheduled(Sgd(), constant(0.5)), max_norm=10.0)
    params = {"w": jnp.ones(2)}
    state = opt.init(params)
    p, state = opt.update({"w": jnp.ones(2)}, state, params)
    np.testing.assert_allclose(np.asarray(p["w"]), 0.5, rtol=1e-6)
    assert int(state["t"]) == 1


def test_validation_and_factory():
    with pytest.raises(ValueError, match="base optimizer"):
        ClipByGlobalNorm(max_norm=1.0)
    assert isinstance(make_optimizer("adamw", 1e-3, weight_decay=0.1), AdamW)


def test_adamw_trains_lenet():
    from tpudml.data.datasets import synthetic_classification
    from tpudml.models import LeNet
    from tpudml.core.prng import seed_key
    from tpudml.train import TrainState, make_train_step

    model = LeNet()
    opt = ClipByGlobalNorm(AdamW(lr=1e-3, weight_decay=0.01), max_norm=5.0)
    images, labels = synthetic_classification(32, (28, 28, 1), 10, seed=0)
    step = make_train_step(model, opt)
    ts = TrainState.create(model, opt, seed_key(0))
    first = None
    for _ in range(8):
        ts, m = step(ts, jnp.asarray(images), jnp.asarray(labels))
        first = first if first is not None else float(m["loss"])
    assert float(m["loss"]) < first


def test_sharded_clip_matches_global_norm():
    """ClipByGlobalNorm(axes=...) inside shard_map (device-local shards for
    some leaves, replicated others) must produce the same update as the
    plain clip applied to the full gathered tree."""
    from jax.sharding import PartitionSpec as P

    from tpudml.core.config import MeshConfig
    from tpudml.core.dist import make_mesh
    from tpudml.parallel.sharding import shard_map_fn

    W = 4
    mesh = make_mesh(MeshConfig({"x": W}), jax.devices()[:W])
    rng = np.random.default_rng(0)
    params = {
        "shard": jnp.asarray(rng.normal(size=(W, 4)).astype(np.float32)),
        "rep": jnp.asarray(rng.normal(size=(3,)).astype(np.float32)),
    }
    grads = {
        "shard": jnp.asarray(rng.normal(size=(W, 4)).astype(np.float32) * 3),
        "rep": jnp.asarray(rng.normal(size=(3,)).astype(np.float32) * 3),
    }

    def is_shard(path):
        return getattr(path[0], "key", None) == "shard"

    opt = ClipByGlobalNorm(Sgd(lr=1.0), max_norm=0.5, axes=("x",), sharded=is_shard)
    spec = {"shard": P("x"), "rep": P()}

    def upd(g, p):
        new_p, _ = opt.update(g, (), p)
        return new_p

    sharded_out = jax.jit(
        shard_map_fn(upd, mesh, in_specs=(spec, spec), out_specs=spec)
    )(grads, params)

    ref_opt = ClipByGlobalNorm(Sgd(lr=1.0), max_norm=0.5)
    ref_out, _ = ref_opt.update(grads, (), params)
    for k in params:
        np.testing.assert_allclose(
            np.asarray(sharded_out[k]), np.asarray(ref_out[k]), rtol=1e-6
        )


def test_shard_aware_clip_recurses_into_wrapper_chains():
    """ADVICE r2: a clip nested below the top of the optimizer chain must
    be rewrapped too, or it would compute per-shard norms inside
    shard_map. (Scheduled refuses a clip base at construction — the
    reachable nesting is a clip under another clip, and the recursion
    covers any future wrapper with a ``.base``.)"""
    from tpudml.optim import shard_aware_clip

    nested = ClipByGlobalNorm(
        max_norm=5.0, axes=("stage",),
        base=ClipByGlobalNorm(max_norm=1.0, base=Sgd(lr=0.1)),
    )
    out = shard_aware_clip(nested, ("stage",), None)
    assert out.axes == ("stage",)  # outer untouched (already axed)
    assert out.base.axes == ("stage",)  # inner rewrapped by recursion
    # Idempotent, and pass-through on plain optimizers.
    again = shard_aware_clip(out, ("data",), None)
    assert again.base.axes == ("stage",)
    assert shard_aware_clip(Sgd(lr=0.1), ("data",), None) == Sgd(lr=0.1)
    # Clip under Scheduled is rejected by Scheduled itself (lr contract).
    with pytest.raises(ValueError, match="lr"):
        Scheduled(base=ClipByGlobalNorm(max_norm=1.0, base=Sgd(lr=0.1)),
                  schedule=constant(0.1))
