"""Step-sentinel tests (docs/RESILIENCE.md): the in-graph guard must
skip anomalous updates bit-exactly, compose with the DP engines
(plain / ZeRO-1 / FSDP), name the poisoned leaf and microbatch in its
escalation, and cost nothing when the fault never fires.

The injected faults come from tpudml.resilience.faults — seeded and
deterministic, so every assertion here is exact, not statistical.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpudml.core.config import MeshConfig
from tpudml.core.dist import make_mesh
from tpudml.core.prng import seed_key
from tpudml.models import ForwardMLP, LeNet
from tpudml.optim import make_optimizer
from tpudml.optim.zero1 import ZeRO1
from tpudml.parallel.dp import DataParallel
from tpudml.parallel.fsdp import FSDP
from tpudml.resilience import (
    GradSentinel,
    SentinelTripped,
    attach_sentinel,
    corrupt_microbatch,
    find_sentinel,
    find_sentinel_state,
    param_leaf_names,
    sentinel_hook,
    sentinel_stats,
)

WORLD = 2
GLOBAL = 16


@pytest.fixture(scope="module")
def mesh():
    return make_mesh(MeshConfig({"data": WORLD}), jax.devices()[:WORLD])


@pytest.fixture(scope="module")
def batch():
    rng = np.random.default_rng(0)
    x = rng.normal(size=(GLOBAL, 28, 28, 1)).astype(np.float32)
    y = rng.integers(0, 10, size=(GLOBAL,)).astype(np.int32)
    return x, y


def leaves_equal(a, b):
    fa, fb = jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)
    assert len(fa) == len(fb)
    return all(
        np.array_equal(np.asarray(u), np.asarray(v), equal_nan=True)
        for u, v in zip(fa, fb)
    )


def snapshot(tree):
    return jax.tree.map(lambda a: np.asarray(a), tree)


# ----------------------------------------------------- engine composition


@pytest.mark.parametrize("zero1", [False, True], ids=["plain", "zero1"])
def test_nan_step_skipped_bit_exact(mesh, batch, zero1):
    """The acceptance-criterion parity: a poisoned step increments the
    skip counter, leaves params AND base optimizer state bit-identical,
    and the post-recovery trajectory matches a run where the poisoned
    batch never arrived."""
    x, y = batch
    xbad = corrupt_microbatch(x, "nan", seed=1)

    dp = DataParallel(LeNet(), make_optimizer("adam", 1e-3), mesh,
                      zero1=zero1, sentinel=True)
    step = dp.make_train_step()

    # Chain A, never sees the poison (separate chain: the step donates
    # its TrainState, so a shared prefix cannot be forked).
    ts_a = dp.create_state(seed_key(0))
    ts_a, _ = step(ts_a, x, y)
    ts_a, _ = step(ts_a, x, y)

    # Chain B: clean, poisoned (skipped), clean.
    ts_b = dp.create_state(seed_key(0))
    ts_b, _ = step(ts_b, x, y)  # clean step: Adam moments non-trivial
    p_before = snapshot(ts_b.params)

    ts_b, m2 = step(ts_b, xbad, y)
    st = sentinel_stats(ts_b.opt_state)
    assert st["skips"] == 1 and st["consecutive"] == 1
    assert st["bad_leaf"] >= 0
    assert int(m2["bad_micro"]) == 0  # single microbatch, tainted
    assert leaves_equal(ts_b.params, p_before), "params changed on a skipped step"

    # Recovery: counter resets, and the continued trajectory is bit-exact
    # with the chain that never saw the poisoned batch (rng-free step, so
    # the only state is params + opt state — both carried forward exactly).
    ts_b, _ = step(ts_b, x, y)
    st3 = sentinel_stats(ts_b.opt_state)
    assert st3["consecutive"] == 0 and st3["skips"] == 1
    assert leaves_equal(ts_b.params, ts_a.params)
    assert leaves_equal(
        find_sentinel_state(ts_b.opt_state)["base"],
        find_sentinel_state(ts_a.opt_state)["base"],
    )


def test_inf_skip_under_fsdp(mesh):
    rng = np.random.default_rng(3)
    x = rng.normal(size=(8, 784)).astype(np.float32)
    y = rng.integers(0, 10, size=(8,)).astype(np.int32)
    xbad = corrupt_microbatch(x, "inf", seed=2)

    eng = FSDP(ForwardMLP(), make_optimizer("adam", 1e-3), mesh, sentinel=True)
    ts = eng.create_state(seed_key(0))
    step = eng.make_train_step()
    ts, _ = step(ts, x, y)
    p_before = snapshot(ts.params)
    ts2, _ = step(ts, xbad, y)
    st = sentinel_stats(ts2.opt_state)
    assert st["skips"] == 1
    assert leaves_equal(ts2.params, p_before)


def test_accum_taint_names_poisoned_microbatch(mesh, batch):
    """Under gradient accumulation the taint tracker reports the FIRST
    poisoned microbatch index, not just "something was NaN"."""
    x, y = batch
    accum = 2
    # Replica 0 holds global rows [0:8]; its microbatch 1 is rows [4:8].
    xbad = x.copy()
    xbad[5, 3, 3, 0] = np.nan

    dp = DataParallel(LeNet(), make_optimizer("sgd", 0.01), mesh,
                      accum_steps=accum, sentinel=True)
    ts = dp.create_state(seed_key(0))
    step = dp.make_train_step()
    ts, m = step(ts, x, y)
    assert int(m["bad_micro"]) == -1  # clean
    ts2, m2 = step(ts, xbad, y)
    assert int(m2["bad_micro"]) == 1
    assert sentinel_stats(ts2.opt_state)["skips"] == 1


def test_hook_escalates_past_budget(mesh, batch):
    """sentinel_hook raises SentinelTripped once the CONSECUTIVE skip
    count exceeds the budget, naming the first non-finite leaf and the
    poisoned microbatch — and stays quiet within budget."""
    x, y = batch
    xbad = corrupt_microbatch(x, "nan", seed=4)

    dp = DataParallel(LeNet(), make_optimizer("adam", 1e-3), mesh,
                      sentinel={"skip_budget": 1})
    assert dp.sentinel is not None and dp.sentinel.skip_budget == 1
    ts = dp.create_state(seed_key(0))
    step = dp.make_train_step()
    hook = sentinel_hook(dp.sentinel, ts.params)

    ts, m = step(ts, xbad, y)  # consecutive = 1 == budget: tolerated
    hook(step=1, train_state=ts, metrics=m)
    ts, m = step(ts, xbad, y)  # consecutive = 2 > budget: escalate
    with pytest.raises(SentinelTripped, match="2 consecutive") as exc:
        hook(step=2, train_state=ts, metrics=m)
    names = param_leaf_names(ts.params)
    st = sentinel_stats(ts.opt_state)
    assert names[st["bad_leaf"]] in str(exc.value)
    assert "microbatch 0" in str(exc.value)


def test_hook_noop_without_sentinel(mesh, batch):
    """On a plain engine the hook finds no sentinel state and must not
    crash (same hook list can be installed unconditionally)."""
    x, y = batch
    dp = DataParallel(LeNet(), make_optimizer("adam", 1e-3), mesh)
    ts = dp.create_state(seed_key(0))
    sent = GradSentinel(make_optimizer("adam", 1e-3), skip_budget=1)
    sentinel_hook(sent)(step=1, train_state=ts, metrics={})


# ------------------------------------------------- optimizer-level guard


def _sgd_sentinel(**kw):
    return GradSentinel(make_optimizer("sgd", 0.1), **kw)


def test_spike_guard_arms_after_warmup():
    """The norm-spike test must stay DISARMED through warmup (early
    training norms are noisy) and then skip a step whose norm exceeds
    spike_factor x the running EMA."""
    sent = _sgd_sentinel(spike_factor=5.0, warmup_steps=2, ema_decay=0.5)
    params = {"w": jnp.ones(4)}
    state = sent.init(params)
    small = {"w": jnp.full(4, 0.1)}
    huge = {"w": jnp.full(4, 100.0)}

    # A spike during warmup passes (finite, guard not armed yet).
    p, s = sent.update(huge, state, params)
    assert not np.array_equal(np.asarray(p["w"]), np.asarray(params["w"]))
    assert int(s["skips"]) == 0

    for _ in range(2):  # arm: two good steps at small norm
        params, state = sent.update(small, state, params)
    assert int(state["good_steps"]) == 2
    ema_before = float(state["norm_ema"])

    p2, s2 = sent.update(huge, state, params)
    assert int(s2["skips"]) == 1 and int(s2["consecutive"]) == 1
    assert np.array_equal(np.asarray(p2["w"]), np.asarray(params["w"]))
    # A skipped step must not pollute the EMA the guard compares against.
    assert float(s2["norm_ema"]) == ema_before
    # bad_leaf stays -1: the spike was finite, no leaf to blame.
    assert int(s2["bad_leaf"]) == -1


def test_outlier_passes_without_spike_guard():
    """A finite outlier gradient is NOT caught by the finiteness test
    alone — that is exactly what spike_factor exists for."""
    sent = _sgd_sentinel()  # spike_factor=0: finiteness only
    params = {"w": jnp.ones(4)}
    state = sent.init(params)
    outlier = {"w": jnp.full(4, 1e30)}
    _, s = sent.update(outlier, state, params)
    assert int(s["skips"]) == 0


def test_state_leaves_are_distinct_buffers():
    """Engines donate the TrainState; XLA rejects one buffer appearing at
    two donated positions, so every sentinel counter must be its own
    array (regression: a shared zeros() scalar deadlocked the DP step)."""
    sent = _sgd_sentinel()
    state = sent.init({"w": jnp.ones(2)})
    scalars = [state[k] for k in ("skips", "consecutive", "good_steps")]
    assert len({id(x) for x in scalars}) == len(scalars)


def test_constructor_validation():
    with pytest.raises(ValueError, match="base optimizer"):
        GradSentinel()
    with pytest.raises(ValueError, match="skip_budget"):
        _sgd_sentinel(skip_budget=0)
    with pytest.raises(ValueError, match="spike_factor"):
        _sgd_sentinel(spike_factor=0.5)


# ------------------------------------------------------------- placement


def test_attach_sentinel_goes_inside_zero1():
    """attach_sentinel must guard the post-reduce-scatter chunk grads:
    the ZeRO-1 wrapper stays outermost and the data axis is appended to
    the sentinel's psum axes (chunks are disjoint over it)."""
    base = make_optimizer("adam", 1e-3)
    z = ZeRO1(base, axis_name="data", world=WORLD)
    out = attach_sentinel(z, ())
    assert isinstance(out, ZeRO1)
    assert isinstance(out.base, GradSentinel)
    assert out.base.axis_names == ("data",)
    assert find_sentinel(out) is out.base

    plain = attach_sentinel(base, ())
    assert isinstance(plain, GradSentinel)
    assert plain.axis_names == ()
    assert find_sentinel(plain) is plain


def test_find_sentinel_state_in_nested_tree():
    sent = _sgd_sentinel()
    st = sent.init({"w": jnp.ones(2)})
    nested = {"outer": (st, {"noise": 1})}
    assert find_sentinel_state(nested) is st
    assert find_sentinel_state({"a": [1, 2]}) is None
    with pytest.raises(ValueError, match="no GradSentinel"):
        sentinel_stats({"a": 1})


# ---------------------------------------------------- fault determinism


def test_corrupt_microbatch_is_seeded_and_scoped():
    x = np.random.default_rng(0).normal(size=(8, 4)).astype(np.float32)
    a = corrupt_microbatch(x, "nan", micro=1, accum_steps=4, seed=9)
    b = corrupt_microbatch(x, "nan", micro=1, accum_steps=4, seed=9)
    np.testing.assert_array_equal(a, b)  # same seed, same poison
    c = corrupt_microbatch(x, "nan", micro=1, accum_steps=4, seed=10)
    assert not np.array_equal(a, c, equal_nan=True)
    # Only microbatch 1 (rows 2:4) is touched; the original is untouched.
    assert np.isfinite(x).all()
    assert np.isnan(a[2:4]).any()
    np.testing.assert_array_equal(a[:2], x[:2])
    np.testing.assert_array_equal(a[4:], x[4:])
    with pytest.raises(ValueError, match="unknown corruption"):
        corrupt_microbatch(x, "gamma_ray")
    with pytest.raises(ValueError, match="out of range"):
        corrupt_microbatch(x, "nan", micro=4, accum_steps=4)
