"""Gradient accumulation tests: micro-batched grads are the same
optimization as the full-batch step (equal-size chunks ⇒ mean of chunk
means == batch mean), single-device and under DP."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpudml.core.config import MeshConfig
from tpudml.core.dist import make_mesh
from tpudml.core.prng import seed_key
from tpudml.data.datasets import synthetic_classification
from tpudml.models import LeNet
from tpudml.optim import make_optimizer
from tpudml.parallel.dp import DataParallel
from tpudml.train import TrainState, make_train_step


@pytest.fixture(scope="module")
def batch():
    images, labels = synthetic_classification(32, (28, 28, 1), 10, seed=0)
    return jnp.asarray(images), jnp.asarray(labels)


def test_accum_matches_full_batch(batch):
    images, labels = batch
    model = LeNet()
    opt = make_optimizer("sgd", 0.05, momentum=0.9)
    results = []
    for accum in (1, 4):
        ts = TrainState.create(model, opt, seed_key(0))
        step = make_train_step(model, opt, accum_steps=accum)
        for _ in range(3):
            ts, m = step(ts, images, labels)
        results.append((ts, float(m["loss"])))
    (ts1, l1), (ts4, l4) = results
    np.testing.assert_allclose(l1, l4, rtol=1e-5)
    for a, b in zip(jax.tree.leaves(ts1.params), jax.tree.leaves(ts4.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-6)


def test_dp_with_accum_matches_plain_dp(batch):
    images, labels = batch
    model = LeNet()
    opt = make_optimizer("sgd", 0.05, momentum=0.9)
    mesh = make_mesh(MeshConfig({"data": 4}), jax.devices()[:4])
    states = []
    for accum in (1, 2):
        dp = DataParallel(model, opt, mesh, accum_steps=accum)
        ts = dp.create_state(seed_key(1))
        step = dp.make_train_step()
        for _ in range(2):
            ts, m = step(ts, images, labels)
        states.append(ts)
    for a, b in zip(
        jax.tree.leaves(states[0].params), jax.tree.leaves(states[1].params)
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-6)


def test_split_step_honors_accum(batch):
    """measure_comm mode must accumulate too (same math as fused+accum)."""
    images, labels = batch
    model = LeNet()
    opt = make_optimizer("sgd", 0.05, momentum=0.9)
    mesh = make_mesh(MeshConfig({"data": 4}), jax.devices()[:4])
    dp_fused = DataParallel(model, opt, mesh, accum_steps=2)
    dp_split = DataParallel(model, opt, mesh, measure_comm=True, accum_steps=2)
    ts_f = dp_fused.create_state(seed_key(2))
    ts_s = dp_split.create_state(seed_key(2))
    step_f, step_s = dp_fused.make_train_step(), dp_split.make_train_step()
    for _ in range(2):
        ts_f, _ = step_f(ts_f, images, labels)
        ts_s, _ = step_s(ts_s, images, labels)
    for a, b in zip(jax.tree.leaves(ts_f.params), jax.tree.leaves(ts_s.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-6)


def test_gspmd_with_accum_matches_plain(batch):
    from tpudml.models import lenet_stages
    from tpudml.parallel.mp import GSPMDParallel

    images, labels = batch
    model = lenet_stages()
    opt = make_optimizer("sgd", 0.05, momentum=0.9)
    mesh = make_mesh(MeshConfig({"stage": 2}), jax.devices()[:2])
    states = []
    for accum in (1, 4):
        mp = GSPMDParallel(model, opt, mesh, accum_steps=accum)
        ts = mp.create_state(seed_key(3))
        step = mp.make_train_step()
        for _ in range(2):
            ts, _ = step(ts, images, labels)
        states.append(ts)
    for a, b in zip(
        jax.tree.leaves(states[0].params), jax.tree.leaves(states[1].params)
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-6)


def test_indivisible_batch_raises(batch):
    images, labels = batch
    model = LeNet()
    opt = make_optimizer("sgd", 0.05)
    step = make_train_step(model, opt, accum_steps=5)  # 32 % 5 != 0
    ts = TrainState.create(model, opt, seed_key(0))
    with pytest.raises(ValueError, match="not divisible"):
        step(ts, images, labels)
