"""Launcher tests: templating, env contract, failure containment, and a
real 2-process jax.distributed job (the multi-node-without-a-cluster story,
SURVEY.md §4, as an automated fixture instead of manual terminals)."""

import io
import sys

import pytest

from tpudml.launch import ClusterSpec, launch

PY = sys.executable


def test_spec_json_roundtrip(tmp_path):
    spec = ClusterSpec(
        num_processes=3,
        bottleneck_rank=1,
        rank_env={0: {"FOO": "bar"}},
        timeout_s=12.5,
    )
    path = tmp_path / "cluster.json"
    spec.to_json(path)
    back = ClusterSpec.from_json(path)
    assert back == spec


def test_spec_json_unknown_field_rejected(tmp_path):
    path = tmp_path / "bad.json"
    path.write_text('{"num_processes": 2, "imagee": "typo"}')
    with pytest.raises(ValueError, match="unknown ClusterSpec fields"):
        ClusterSpec.from_json(path)


def test_env_contract_and_templating():
    """Each rank sees the TPUDML_* rendezvous vars and {rank}/{world}
    substitution; all ranks agree on the coordinator."""
    sink = io.StringIO()
    spec = ClusterSpec(num_processes=2)
    code = (
        "import os;"
        "print(os.environ['TPUDML_PROCESS_ID'], os.environ['TPUDML_NUM_PROCESSES'],"
        " os.environ['TPUDML_COORDINATOR'], 'arg={rank}/{world}')"
    )
    result = launch([PY, "-c", code], spec, sink=sink)
    assert result.success, sink.getvalue()
    lines = sorted(sink.getvalue().strip().splitlines())
    assert len(lines) == 2
    coord = lines[0].split()[4]
    assert coord.startswith("127.0.0.1:")
    assert f"[rank 0] 0 2 {coord} arg=0/2" in lines
    assert f"[rank 1] 1 2 {coord} arg=1/2" in lines


def test_failure_containment():
    """One rank dying must take the whole job down promptly — the
    reference's hang-forever gap (SURVEY.md §5.3)."""
    spec = ClusterSpec(num_processes=2, grace_s=2.0)
    code = "import sys,time; sys.exit(1) if {rank} == 1 else time.sleep(60)"
    result = launch([PY, "-c", code], spec, sink=io.StringIO())
    assert not result.success
    assert result.failed_rank == 1
    assert result.returncodes[1] == 1
    assert result.returncodes[0] != 0  # terminated, not left hanging
    assert result.elapsed_s < 30


def test_timeout():
    spec = ClusterSpec(num_processes=2, timeout_s=1.0, grace_s=1.0)
    result = launch([PY, "-c", "import time; time.sleep(60)"], spec, sink=io.StringIO())
    assert not result.success
    assert result.timed_out
    assert result.elapsed_s < 20


def test_restart_policy_recovers(tmp_path):
    """Elastic recovery: a job that crashes once succeeds on relaunch
    (the crash-marker file makes attempt 1 fail, attempt 2 pass)."""
    marker = tmp_path / "crashed-once"
    sink = io.StringIO()
    spec = ClusterSpec(num_processes=2, max_restarts=2, grace_s=2.0)
    code = (
        "import os, sys;"
        "m = " + repr(str(marker)) + " + '.{rank}';"  # per-rank marker
        "crashed = os.path.exists(m);"
        "open(m, 'w').close();"
        "sys.exit(0 if crashed or {rank} == 0 else 3)"
    )
    result = launch([PY, "-c", code], spec, sink=sink)
    assert result.success, sink.getvalue()
    assert result.attempts == 2
    assert "restart 1/2" in sink.getvalue()


def test_restart_policy_gives_up(tmp_path):
    spec = ClusterSpec(num_processes=1, max_restarts=2, grace_s=1.0)
    sink = io.StringIO()
    result = launch([PY, "-c", "import sys; sys.exit(7)"], spec, sink=sink)
    assert not result.success
    assert result.attempts == 3  # initial + 2 restarts
    assert result.returncodes == [7]


def test_same_program_check_catches_config_divergence(tmp_path):
    """Ranks launched with different hyperparameters must fail fast with an
    attributed error instead of deadlocking in the first collective
    (SURVEY.md §5.2)."""
    sink = io.StringIO()
    spec = ClusterSpec(num_processes=2, timeout_s=240.0)
    result = launch(
        [PY, "-m", "tasks.task2", "--dataset", "synthetic", "--epochs", "1",
         "--log_every", "0", "--n_devices", "2", "--lr", "0.0{rank}1"],
        spec,
        sink=sink,
    )
    out = sink.getvalue()
    assert not result.success
    assert "SPMD task config mismatch" in out
    assert result.elapsed_s < 120


def test_two_process_sharded_checkpoint(tmp_path):
    """Per-host checkpointing across real processes: each rank writes only
    its addressable shards; both reassemble the full state."""
    sink = io.StringIO()
    spec = ClusterSpec(num_processes=2, timeout_s=240.0)
    code = (
        "import jax, numpy as np, jax.numpy as jnp;"
        "from tpudml.core.config import DistributedConfig, MeshConfig;"
        "from tpudml.core.dist import distributed_init, make_mesh, process_index;"
        "distributed_init(DistributedConfig.from_env());"
        "from jax.sharding import NamedSharding, PartitionSpec as P;"
        "mesh = make_mesh(MeshConfig({'model': 2}));"
        "w = jax.device_put(jnp.arange(8.0).reshape(2, 4), NamedSharding(mesh, P('model')));"
        "tree = {'w': w};"
        "from tpudml.checkpoint import save_sharded_checkpoint, restore_sharded_checkpoint;"
        f"p = save_sharded_checkpoint({str(tmp_path)!r}, tree, step=7);"
        "back = restore_sharded_checkpoint(p, {'w': jnp.zeros((2, 4))});"
        "np.testing.assert_array_equal(np.asarray(back['w']), np.arange(8.0).reshape(2, 4));"
        "print(f'rank {process_index()}: sharded ok')"
    )
    result = launch([PY, "-c", code], spec, sink=sink)
    out = sink.getvalue()
    assert result.success, out
    assert "rank 0: sharded ok" in out and "rank 1: sharded ok" in out
    files = sorted(p.name for p in (tmp_path / "step_7").iterdir())
    assert files == [
        "manifest_p0.json", "manifest_p1.json", "shards_p0.npz", "shards_p1.npz",
    ]


def test_two_process_collective_job():
    """End-to-end: 2 ranks initialize jax.distributed via the env contract,
    form a global 2-device mesh, and psum across process boundaries."""
    sink = io.StringIO()
    spec = ClusterSpec(num_processes=2, timeout_s=240.0)
    code = (
        "import jax, numpy as np, jax.numpy as jnp;"
        "from jax.sharding import Mesh, PartitionSpec as P;"
        "from tpudml.core.config import DistributedConfig;"
        "from tpudml.core.dist import distributed_init, process_index, process_count;"
        "distributed_init(DistributedConfig.from_env());"
        "assert process_count() == 2;"
        "mesh = Mesh(np.array(jax.devices()), ('data',));"
        "from tpudml.parallel.sharding import shard_map_fn;"
        "fn = jax.jit(shard_map_fn(lambda x: jax.lax.psum(x, 'data'), mesh, P('data'), P()));"
        "out = fn(jnp.arange(2.0));"
        "print(f'rank {process_index()} psum {float(out[0])}')"
    )
    result = launch([PY, "-c", code], spec, sink=sink)
    out = sink.getvalue()
    assert result.success, out
    assert "[rank 0] rank 0 psum 1.0" in out
    assert "[rank 1] rank 1 psum 1.0" in out


def _one_device_env(n_ranks):
    """Rank env giving each process ONE local CPU device (the launched
    processes inherit pytest's 8-device XLA_FLAGS otherwise, multiplying
    the world size and compile time)."""
    return {
        r: {"XLA_FLAGS": "--xla_force_host_platform_device_count=1"}
        for r in range(n_ranks)
    }


@pytest.mark.slow
def test_three_process_task4_e2e(tmp_path):
    """Launcher-driven task4 across 3 real processes — the reference's
    3-service docker-compose topology (codes/task4/docker-compose.yml) as
    an automated test: stage-sharded LeNet over a 3-device global mesh,
    every rank reporting the SAME test accuracy."""
    import re

    sink = io.StringIO()
    spec = ClusterSpec(
        num_processes=3, timeout_s=420.0, rank_env=_one_device_env(3)
    )
    result = launch(
        [PY, "-m", "tasks.task4", "--dataset", "synthetic", "--epochs", "1",
         "--batch_size", "200", "--log_every", "0"],
        spec,
        sink=sink,
    )
    out = sink.getvalue()
    assert result.success, out
    accs = re.findall(r"Test accuracy: ([0-9.]+)%", out)
    assert len(accs) == 3, out
    assert len(set(accs)) == 1, accs  # all ranks agree (replicated eval)


@pytest.mark.slow
@pytest.mark.parametrize("parallel", ["dp", "cp"])
def test_two_process_task5_e2e(tmp_path, parallel):
    """2-process task5 LM training end-to-end: dp = replicated model over
    a cross-process data mesh; cp = ring-attention context parallelism
    with K/V blocks ppermuting across REAL process boundaries."""
    import re

    sink = io.StringIO()
    spec = ClusterSpec(
        num_processes=2, timeout_s=420.0, rank_env=_one_device_env(2)
    )
    result = launch(
        [PY, "-m", "tasks.task5_longcontext", "--parallel", parallel,
         "--seq_len", "32", "--batch_size", "8", "--vocab", "32",
         "--embed_dim", "32", "--num_heads", "4", "--num_layers", "1",
         "--steps", "20", "--lr", "0.02", "--log_every", "0"],
        spec,
        sink=sink,
    )
    out = sink.getvalue()
    assert result.success, out
    losses = re.findall(r"final loss ([0-9.]+)", out)
    assert len(losses) == 2, out
    assert len(set(losses)) == 1, losses  # ranks agree
    assert float(losses[0]) < 1.0, out  # learned the successor permutation


@pytest.mark.slow
def test_elastic_recovery_resumes_from_checkpoint(tmp_path):
    """The elastic path end-to-end: rank 1 crashes mid-epoch-2 on the
    first attempt; the launcher relaunches (max_restarts), --resume
    restores the epoch-boundary checkpoint, and the job finishes at the
    SAME final step a crash-free run reaches (epoch-granular resume)."""
    import re

    marker = tmp_path / "crashed-once"
    ckpt = tmp_path / "ckpt"
    sink = io.StringIO()
    spec = ClusterSpec(
        num_processes=2, timeout_s=600.0, max_restarts=1, grace_s=5.0,
        rank_env=_one_device_env(2),
    )
    # Wrap task2: a train_loop hook kills rank 1 at step 48 (mid-epoch 2;
    # the 4096-sample synthetic set partitions to 2048/replica, so batch 64
    # = 32 steps/epoch) on the first attempt only. --ckpt_every 32 lands on
    # the epoch boundary (resume granularity is whole epochs). 2 epochs is
    # the minimum that crashes mid-epoch-2 and still resumes past it.
    code = (
        "import os, sys;"
        "import tpudml.train as T;"
        "marker = " + repr(str(marker)) + " + '.once';"
        "rank = int(os.environ['TPUDML_PROCESS_ID']);"
        "orig = T.train_loop;\n"
        "def bomb(step=0, **kw):\n"
        "    if rank == 1 and step == 48 and not os.path.exists(marker):\n"
        "        open(marker, 'w').close(); os._exit(5)\n"
        "def wrapped(*a, **kw):\n"
        "    kw['hooks'] = list(kw.get('hooks') or []) + [bomb]\n"
        "    return orig(*a, **kw)\n"
        "T.train_loop = wrapped\n"
        "from tasks import task2;"
        "task2.main(['--dataset', 'synthetic', '--epochs', '2',"
        " '--batch_size', '64', '--log_every', '0',"
        " '--ckpt_dir', " + repr(str(ckpt)) + ", '--ckpt_every', '32',"
        " '--resume'])"
    )
    result = launch([PY, "-c", code], spec, sink=sink)
    out = sink.getvalue()
    assert result.success, out
    assert result.attempts == 2, out  # crashed once, recovered once
    assert (tmp_path / "crashed-once.once").exists()  # the bomb DID fire
    accs = re.findall(r"Test accuracy: ([0-9.]+)%", out)
    assert len(accs) == 2 and len(set(accs)) == 1, out
    # Resume reached the budgeted final step: 2 epochs x 32 steps.
    from tpudml.checkpoint import CheckpointManager

    assert CheckpointManager(str(ckpt)).latest_step() == 64


def test_tpu_vm_command_builders():
    """Env-bootstrap layer (the reference's env_setup chapter, TPU-VM
    form): the gcloud command builders are the tested contract — stable
    verb order, worker=all fan-out, no per-rank templating (the TPU
    metadata supplies coordinator/rank/world)."""
    from tpudml.launch.tpu_vm import (
        TpuVmSpec, create_command, delete_command, pod_workflow, run_command,
    )

    spec = TpuVmSpec(name="pod0", zone="us-east5-a",
                     accelerator_type="v5litepod-16", project="proj")
    c = create_command(spec)
    assert c[:6] == ["gcloud", "compute", "tpus", "tpu-vm", "create", "pod0"]
    assert "--accelerator-type" in c and "v5litepod-16" in c
    assert "--project" in c and "proj" in c

    r = run_command(spec, "python -m tasks.task2 --epochs 2")
    assert "--worker=all" in r
    assert r[-1] == "python -m tasks.task2 --epochs 2"
    assert not any("{rank}" in part or "MASTER_ADDR" in part for part in r)

    wf = pod_workflow(spec, "python -m tasks.north_star", repo_dir="/src")
    assert [w[4] for w in wf] == ["create", "scp", "ssh", "delete"]
    # The run step cd's into exactly where scp lands the repo
    # (DST/<basename(src)>), for any src — not just ".".
    assert wf[1][6] == "/src" and wf[1][7] == "pod0:~"
    assert "cd ~/src &&" in wf[2][-1]
    assert delete_command(spec)[-1] == "--quiet"


def test_tpu_vm_cli_dry_run(capsys):
    from tpudml.launch import tpu_vm

    rc = tpu_vm.main(["workflow", "--name", "pod1", "--command", "echo hi"])
    assert rc == 0
    out = capsys.readouterr().out
    assert out.count("gcloud compute tpus tpu-vm") == 4
    assert "create pod1" in out and "delete pod1" in out
    assert "echo hi" in out



def test_restart_backoff_is_seeded_and_budgeted(tmp_path):
    """Backoff delays are a pure function of the spec's seed (exponential
    base x factor^(attempt-1) + seeded jitter), recorded on the result,
    and announced in the relaunch message."""
    import random

    spec = ClusterSpec(
        num_processes=1, max_restarts=2, grace_s=1.0,
        restart_backoff_s=0.05, restart_backoff_factor=2.0,
        restart_backoff_jitter=0.5, restart_backoff_seed=42,
    )
    sink = io.StringIO()
    result = launch([PY, "-c", "import sys; sys.exit(7)"], spec, sink=sink)
    assert not result.success
    assert result.attempts == 3

    rng = random.Random(42)
    expected = []
    for attempt in (1, 2):
        d = 0.05 * 2.0 ** (attempt - 1)
        expected.append(d + rng.uniform(0, 0.5 * d))
    assert result.backoffs_s == pytest.approx(expected)
    out = sink.getvalue()
    assert f"after {expected[0]:.2f}s backoff" in out
    assert "restart 1/2" in out and "restart 2/2" in out
    # The spec (with its backoff knobs) still round-trips through JSON.
    path = tmp_path / "spec.json"
    spec.to_json(path)
    assert ClusterSpec.from_json(path) == spec


def test_backoff_defaults_keep_immediate_restart():
    """restart_backoff_s=0 (the default) restarts immediately and emits
    no backoff chatter — existing restart flows are unchanged."""
    sink = io.StringIO()
    spec = ClusterSpec(num_processes=1, max_restarts=1, grace_s=1.0)
    result = launch([PY, "-c", "import sys; sys.exit(3)"], spec, sink=sink)
    assert result.backoffs_s == [0.0]
    assert "backoff" not in sink.getvalue()


def test_rank_kill_containment_and_backoff_recovery(tmp_path):
    """The resilience drill at the process level: faults.rank_kill_hook
    hard-kills rank 1 once (os._exit, no cleanup), the launcher tears
    the job down, waits out the seeded backoff, relaunches, and the
    marker file makes the restarted attempt run clean."""
    marker = str(tmp_path / "killed-once")
    sink = io.StringIO()
    spec = ClusterSpec(
        num_processes=2, max_restarts=1, grace_s=2.0,
        restart_backoff_s=0.01,
    )
    code = (
        "from tpudml.resilience import rank_kill_hook;"
        f"h = rank_kill_hook(3, marker={marker!r}, rank=1);"
        "[h(step=s) for s in range(5)]"
    )
    result = launch([PY, "-c", code], spec, sink=sink)
    assert result.success, sink.getvalue()
    assert result.attempts == 2
    assert result.backoffs_s == [0.01]
    assert "restart 1/1" in sink.getvalue()
    assert (tmp_path / "killed-once").exists()


def test_launch_check_cli():
    """``python -m tpudml.launch --check``: the CI smoke proving the
    multi-process CPU wiring (gloo collectives + rendezvous) end to end
    from the CLI — exit 0 and one correct-psum line per rank."""
    import subprocess

    proc = subprocess.run(
        [PY, "-m", "tpudml.launch", "--check", "--timeout_s", "180"],
        capture_output=True,
        text=True,
        timeout=240,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "[rank 0] [check] rank 0/2 psum 1.0 OK" in proc.stdout
    assert "[rank 1] [check] rank 1/2 psum 1.0 OK" in proc.stdout
    assert "launch --check: OK" in proc.stdout
