"""Long-context task entrypoint tests: every parallel strategy trains the
LM to low loss on the deterministic successor data (8-device CPU mesh)."""

import numpy as np
import pytest

from tasks.task5_longcontext import main
from tpudml.data.datasets import synthetic_lm


def test_synthetic_lm_is_deterministic_successor():
    seqs = synthetic_lm(4, 16, 32, seed=0)
    seqs2 = synthetic_lm(4, 16, 32, seed=0)
    np.testing.assert_array_equal(seqs, seqs2)
    # Same current token ⇒ same next token, everywhere.
    succ = {}
    for row in seqs:
        for a, b in zip(row[:-1], row[1:]):
            assert succ.setdefault(int(a), int(b)) == int(b)


COMMON = [
    "--seq_len", "64", "--batch_size", "8", "--vocab", "32",
    "--embed_dim", "32", "--num_heads", "4", "--num_layers", "1",
    "--steps", "40", "--lr", "0.01", "--log_every", "0",
]


@pytest.mark.parametrize(
    "extra",
    [
        ["--parallel", "single"],
        ["--parallel", "dp", "--n_devices", "4"],
        ["--parallel", "cp", "--n_devices", "4"],
        ["--parallel", "cp", "--n_devices", "4", "--attn", "ulysses"],
        ["--parallel", "tp", "--n_devices", "4"],
        # pp is one block PER STAGE (4 layers here vs 1 above) — the deeper
        # model needs a few more steps to pass the same loss bar.
        pytest.param(
            ["--parallel", "pp", "--n_devices", "4", "--microbatches", "4",
             "--steps", "80"],
            marks=pytest.mark.slow,  # ~14s; pipeline parity lives in test_pp*
        ),
        ["--parallel", "ep", "--n_devices", "4", "--moe_experts", "8"],
        ["--parallel", "single", "--rope", "--num_kv_heads", "2"],
    ],
    ids=["single", "dp", "cp-ring", "cp-ulysses", "tp", "pp", "ep-moe",
         "rope-gqa"],
)
def test_strategies_learn_successor(extra):
    out = main(COMMON + extra)
    assert np.isfinite(out["final_loss"])
    assert out["final_loss"] < 1.0, out


def test_invalid_combinations_rejected():
    with pytest.raises(ValueError, match="requires --parallel cp"):
        main(COMMON + ["--parallel", "dp", "--attn", "ring"])
    with pytest.raises(ValueError, match="cp needs"):
        main(COMMON + ["--parallel", "cp", "--attn", "full"])


def test_sentinel_ckpt_resume_smoke(tmp_path):
    """--sentinel/--ckpt_every/--resume on the dp LM engine: rolling
    saves land under the monotonic step key, a resumed run continues
    from the restored step to the (raised) --steps instead of
    retraining from scratch, and a resume with nothing left is a clear
    error rather than a silent no-op."""
    from tpudml.checkpoint import CheckpointManager

    ckpt = str(tmp_path / "ckpt")
    log = str(tmp_path / "logs")
    base = COMMON + [
        "--parallel", "dp", "--n_devices", "2", "--sentinel",
        "--ckpt_dir", ckpt, "--log_dir", log,
    ]

    out = main(base + ["--steps", "6", "--ckpt_every", "2"])
    assert out["steps_run"] == 6
    mgr = CheckpointManager(ckpt)
    assert mgr.latest_step() == 6

    out2 = main(base + ["--steps", "8", "--resume"])
    assert out2["steps_run"] == 8
    assert np.isfinite(out2["final_loss"])
    assert mgr.latest_step() == 8

    with pytest.raises(ValueError, match="nothing left to run"):
        main(base + ["--steps", "8", "--resume"])


def test_sentinel_rejected_off_supported_engines():
    """cp/ep (and single) have no sentinel slot in their optimizer
    chain; the flag must fail loudly, not silently drop coverage."""
    for strategy in (["--parallel", "cp", "--n_devices", "2"],
                     ["--parallel", "single"]):
        with pytest.raises(ValueError, match="--sentinel composes"):
            main(COMMON + strategy + ["--sentinel"])


def test_resume_requires_ckpt_dir():
    with pytest.raises(SystemExit):
        main(COMMON + ["--parallel", "single", "--resume"])
