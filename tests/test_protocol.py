"""Cross-rank protocol pass (P300–P304): model, rules, gate, fixtures.

Covers the PR 19 surface end to end:

- the fixture-twin contract: every P rule fires on its broken twin and
  stays silent on its healthy twin (filename-keyed discovery under
  ``tests/analysis_fixtures/protocol/``, coverage-pinned);
- the re-mesh property: every single-slot ``replace_pipeline`` shrink
  of the drill's [2,2] pipeline and the 3-stage [2,2,2] spec yields a
  P300/P301-clean schedule — re-mesh never emits an undeliverable
  frame;
- the committed meshless fixtures validate against the schedule model
  (every replayed transfer is a modeled frame; tampered streams fire);
- the ``--protocol`` CLI: strict-clean on the repo, byte-deterministic;
- the MPMDController pre-launch gate: a rejected spec never spawns and
  leaves machine-readable receipts; a clean spec records its receipts
  and launches.
"""

from __future__ import annotations

import importlib.util
import json
import os
import subprocess
import sys
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parents[1]
FIXDIR = REPO / "tests" / "analysis_fixtures" / "protocol"

from tpudml.analysis.ast_pass import analyze_file  # noqa: E402
from tpudml.analysis.protocol import (  # noqa: E402
    analyze_pipeline,
    analyze_protocol_surface,
    build_schedules,
    check_schedules,
    protocol_surface,
    validate_fixture_events,
)
from tpudml.mpmd.spec import replace_pipeline  # noqa: E402


def _fixture_names() -> list:
    return sorted(
        p.stem for p in FIXDIR.glob("p*_*.py") if p.name != "__init__.py"
    )


def _load_fixture(name: str):
    path = FIXDIR / f"{name}.py"
    spec = importlib.util.spec_from_file_location(f"protofix_{name}", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod, path


# ------------------------------------------------------- fixture twins


@pytest.mark.parametrize("name", _fixture_names())
def test_protocol_fixture(name):
    """Each fixture module fires (or stays silent on) exactly its RULE;
    silent twins additionally introduce NO P-series finding at all."""
    mod, path = _load_fixture(name)
    assert mod.EXPECT in ("fire", "silent"), name
    if mod.MODE == "ast":
        findings = [f for f in analyze_file(str(path))
                    if f.rule.startswith("P")]
    else:
        assert mod.MODE == "schedule", name
        spec, schedules = mod.build()
        findings = check_schedules(spec, schedules, entrypoint=name)
    fired = [f for f in findings if f.rule == mod.RULE]
    if mod.EXPECT == "fire":
        assert fired, f"{name}: {mod.RULE} did not fire ({findings})"
    else:
        assert not findings, f"{name}: expected silence, got {findings}"


def test_fixture_dir_covers_every_p_rule():
    """Coverage pin: each of P300–P304 has BOTH a fire and a silent
    twin, so a new P rule cannot land without its seeded evidence."""
    twins: dict = {}
    for name in _fixture_names():
        mod, _ = _load_fixture(name)
        twins.setdefault(mod.RULE, set()).add(mod.EXPECT)
    assert set(twins) == {"P300", "P301", "P302", "P303", "P304"}, twins
    for rule, kinds in twins.items():
        assert kinds == {"fire", "silent"}, (rule, kinds)


# ------------------------------------------------- re-mesh property


@pytest.mark.parametrize("surface_name", ["mpmd_drill", "mpmd_3stage"])
def test_every_single_slot_shrink_stays_protocol_clean(surface_name):
    """replace_pipeline over EVERY single-slot failure must produce a
    spec whose composed schedules are P300/P301-clean — the pre-launch
    gate can never veto a legitimate re-mesh."""
    spec = protocol_surface()[surface_name]
    assert analyze_pipeline(spec) == []
    for slot in range(spec.total_slots):
        shrunk, slot_map = replace_pipeline(spec, {slot})
        findings = analyze_pipeline(
            shrunk, entrypoint=f"{surface_name}:kill{slot}")
        bad = [f for f in findings if f.rule in ("P300", "P301")]
        assert not bad, (surface_name, slot, bad)
        assert slot not in slot_map


def test_simulation_is_exhaustive_on_surface():
    """Every (stage, rank) schedule on the repo surface is non-trivial:
    the model actually contains p2p frames, votes and collectives (a
    degenerate empty model would vacuously pass everything)."""
    for name, spec in sorted(protocol_surface().items()):
        schedules = build_schedules(spec)
        assert len(schedules) == spec.total_slots, name
        kinds = {e.kind for evs in schedules.values() for e in evs}
        if len(spec.stages) > 1:
            assert {"send", "recv"} <= kinds, (name, kinds)
        if any(st.dp > 1 for st in spec.stages):
            assert {"vote", "collective"} <= kinds, (name, kinds)


# --------------------------------------------- fixture stream model


@pytest.mark.parametrize("fixture", ["steady", "shrink_stage"])
def test_committed_fixture_streams_match_schedule_model(fixture):
    """Satellite 2: every replayed transfer event corresponds to a
    modeled act frame (edge, plan index, byte count) of the pipeline
    incarnation it ran under — goldens and checker cannot silently
    diverge."""
    path = REPO / "tests" / "mpmd_fixtures" / f"{fixture}.json"
    assert validate_fixture_events(path) == []


def test_tampered_fixture_stream_fires_p300():
    """Mutating a single replayed transfer line (wrong edge; wrong byte
    count) is caught against the schedule model."""
    from tpudml.mpmd.fixture import replay_fixture

    doc = json.loads(
        (REPO / "tests" / "mpmd_fixtures" / "steady.json").read_text())
    lines = replay_fixture(dict(doc))["lines"]

    def tamper(mutate):
        out = list(lines)
        for i, line in enumerate(out):
            ev = json.loads(line)
            if ev.get("event") == "transfer":
                mutate(ev)
                out[i] = json.dumps(
                    ev, sort_keys=True, separators=(",", ":"))
                break
        return out

    wrong_edge = validate_fixture_events(
        doc, lines=tamper(lambda ev: ev.update(edge="s9r9->s9r9")))
    assert any(f.rule == "P300" for f in wrong_edge), wrong_edge
    wrong_bytes = validate_fixture_events(
        doc, lines=tamper(lambda ev: ev.update(bytes=ev["bytes"] + 1)))
    assert any(f.rule == "P300" for f in wrong_bytes), wrong_bytes
    dropped = validate_fixture_events(
        doc,
        lines=[l for l in lines
               if json.loads(l).get("event") != "transfer"
               or json.loads(l).get("index") != 0
               or json.loads(l).get("step") != 0],
    )
    assert any("omitted modeled frame" in f.message for f in dropped), dropped


# ------------------------------------------------- traced signatures


def test_traced_collective_signatures_drive_p302():
    """collective_shape_signature extracts (op, axes, shape) from a real
    traced program, and injecting divergent per-rank signatures fires
    P302 while identical ones stay silent."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, PartitionSpec as P
    from jax.experimental.shard_map import shard_map

    import numpy as np

    from tpudml.analysis.protocol import traced_collective_events

    mesh = Mesh(np.asarray(jax.devices("cpu")[:2]), ("data",))

    def make(width):
        @jax.jit
        def fn(x):
            return shard_map(
                lambda v: jax.lax.psum(v, "data"), mesh=mesh,
                in_specs=P("data"), out_specs=P(),
            )(x)

        return traced_collective_events(fn, (jnp.ones((2, width)),))

    sig_a, sig_b = make(4), make(8)
    assert sig_a and sig_a[0][0] == "psum", sig_a
    assert sig_a != sig_b

    spec = protocol_surface()["mpmd_drill"]
    silent = check_schedules(
        spec, build_schedules(spec, stage_collectives={0: sig_a, 1: sig_a}))
    assert silent == [], silent
    mixed = build_schedules(
        spec, stage_collectives={(0, 0): sig_a, (0, 1): sig_b, 1: sig_a})
    fired = [f for f in check_schedules(spec, mixed) if f.rule == "P302"]
    assert len(fired) == 1, fired


# --------------------------------------------------------------- CLI


def _run_cli(*cli_args, timeout=180):
    return subprocess.run(
        [sys.executable, "-m", "tpudml.analysis", *cli_args],
        cwd=REPO, capture_output=True, text=True, timeout=timeout,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )


def test_protocol_cli_strict_green_and_deterministic():
    """Satellite 4 (protocol slice): ``--protocol --strict`` exits 0
    with zero findings on the real surface, and the report is
    byte-deterministic across runs."""
    first = _run_cli("--protocol", "--strict")
    assert first.returncode == 0, first.stdout + first.stderr
    assert "0 finding(s)" in first.stdout
    second = _run_cli("--protocol", "--strict")
    assert second.stdout == first.stdout


def test_protocol_cli_json_names_surface():
    """--protocol --format json emits the machine shape with zero
    active findings, and the checked surface itself (drill + 3stage +
    the committed fixtures including their post-kill shrinks) is
    pinned."""
    names = set(protocol_surface())
    assert {"mpmd_drill", "mpmd_3stage", "fixture:steady",
            "fixture:shrink_stage",
            "fixture:shrink_stage:after_kill3"} <= names
    proc = _run_cli("--protocol", "--format", "json")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    out = json.loads(proc.stdout)
    assert set(out) == {"active", "allowed", "stale_allowlist"}
    assert out["active"] == []
    # Partial run: --protocol never judges allowlist staleness.
    assert out["stale_allowlist"] == []


def test_full_surface_findings_cover_protocol():
    """The default full run folds the protocol surface in (what
    --strict CI gates); here we pin the in-process equivalent."""
    assert analyze_protocol_surface() == []


# ------------------------------------------------- controller gate


def _controller(tmp_path, checker, cmd=None):
    from tpudml.launch.cluster import ClusterSpec
    from tpudml.mpmd.groups import MPMDController

    spec = protocol_surface()["mpmd_drill"]
    return MPMDController(
        cmd or [sys.executable, "-c", "pass"],
        spec,
        ClusterSpec(timeout_s=120.0),
        run_dir=tmp_path / "run",
        ckpt_dir=tmp_path / "ckpt",
        max_reforms=1,
        protocol_checker=checker,
        sink=open(os.devnull, "w"),
    )


def test_controller_refuses_rejected_spec(tmp_path):
    """A spec the checker rejects never spawns: no round records, a
    ``protocol_rejected`` stop reason, and machine-readable receipts in
    both the result and ``protocol_report.json``."""
    from tpudml.analysis.findings import Finding

    calls = []

    def reject(pipeline):
        calls.append(pipeline)
        return [Finding("P300", "injected asymmetry",
                        entrypoint="protocol:test")]

    ctl = _controller(
        tmp_path, reject,
        cmd=[sys.executable, "-c", "raise SystemExit(9)"])
    res = ctl.run()
    assert len(calls) == 1
    assert res.stop_reason == "protocol_rejected"
    assert res.records == [] and not res.success
    assert len(res.protocol) == 1 and res.protocol[0]["ok"] is False
    assert res.protocol[0]["findings"][0]["rule"] == "P300"
    assert res.to_dict()["protocol"] == res.protocol
    report = json.loads(
        (tmp_path / "run" / "protocol_report.json").read_text())
    assert report["ok"] is False
    assert report["checks"][0]["findings"][0]["severity"] == "error"
    # obs_report surfaces the verdict next to the MPMD section.
    sys.path.insert(0, str(REPO / "tools"))
    try:
        import obs_report
    finally:
        sys.path.pop(0)
    text = obs_report.report(tmp_path)
    assert "protocol gate" in text and "REJECTED at round 0" in text


def test_controller_gate_passes_clean_spec_with_receipts(tmp_path):
    """The real checker on the drill spec: the pipeline launches (one
    trivially-exiting round), the receipt is recorded clean, and the
    report file says ok."""
    ctl = _controller(tmp_path, None)  # default = analyze_pipeline
    res = ctl.run()
    assert res.stop_reason == "success", res.stop_reason
    assert res.success and len(res.records) == 1
    assert [r["ok"] for r in res.protocol] == [True]
    assert res.protocol[0]["findings"] == []
    report = json.loads(
        (tmp_path / "run" / "protocol_report.json").read_text())
    assert report["ok"] is True and len(report["checks"]) == 1
    sys.path.insert(0, str(REPO / "tools"))
    try:
        import obs_report
    finally:
        sys.path.pop(0)
    assert "protocol gate" in obs_report.report(tmp_path)
