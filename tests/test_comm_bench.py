"""Comm micro-benchmark tool smoke test (task2's strategy comparison)."""

from tpudml.comm.bench import main


def test_comm_bench_runs_all_strategies(capsys):
    results = main(["--iters", "2", "--sizes", "4096", "--n_devices", "4"])
    assert {r["strategy"] for r in results} == {
        "allgather", "allreduce", "reducescatter",
    }
    assert all(r["mean_ms"] > 0 and r["world"] == 4 for r in results)
    out = capsys.readouterr().out
    assert "allreduce" in out and "4096" in out
