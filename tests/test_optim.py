"""Unit tests: optimizers against analytic updates (SURVEY.md §4 pyramid),
including the reference's no-bias-correction Adam variant."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpudml.optim import Adam, GradientDescent, ReferenceAdam, Sgd, make_optimizer


def tree_allclose(a, b, **kw):
    flat_a, _ = jax.tree.flatten(a)
    flat_b, _ = jax.tree.flatten(b)
    for x, y in zip(flat_a, flat_b):
        np.testing.assert_allclose(x, y, **kw)


@pytest.fixture
def params():
    return {"w": jnp.array([1.0, -2.0, 3.0]), "b": jnp.array(0.5)}


@pytest.fixture
def grads():
    return {"w": jnp.array([0.1, 0.2, -0.3]), "b": jnp.array(1.0)}


def test_gd_analytic(params, grads):
    opt = GradientDescent(lr=0.1)
    state = opt.init(params)
    new, _ = opt.update(grads, state, params)
    tree_allclose(
        new, {"w": jnp.array([0.99, -2.02, 3.03]), "b": jnp.array(0.4)}, rtol=1e-6
    )


def test_sgd_momentum_matches_torch_formula(params, grads):
    # torch.optim.SGD: buf = mu*buf + g ; p -= lr*buf  (first step buf = g)
    opt = Sgd(lr=0.01, momentum=0.9)
    state = opt.init(params)
    p1, s1 = opt.update(grads, state, params)
    tree_allclose(p1, jax.tree.map(lambda p, g: p - 0.01 * g, params, grads), rtol=1e-6)
    p2, _ = opt.update(grads, s1, p1)
    # second step buf = 0.9*g + g = 1.9*g
    tree_allclose(p2, jax.tree.map(lambda p, g: p - 0.01 * 1.9 * g, p1, grads), rtol=1e-6)


def test_reference_adam_no_bias_correction(params, grads):
    """First-step update must be lr * (1-b1)*g / (sqrt((1-b2)*g^2) + eps) —
    the uncorrected form (reference MyOptimizer.py:35-43), NOT ≈ lr*sign(g)."""
    lr, b1, b2, eps = 0.01, 0.9, 0.999, 1e-8
    opt = ReferenceAdam(lr=lr, b1=b1, b2=b2, eps=eps)
    new, _ = opt.update(grads, opt.init(params), params)
    expected = jax.tree.map(
        lambda p, g: p - lr * (1 - b1) * g / (jnp.sqrt((1 - b2) * g * g) + eps),
        params,
        grads,
    )
    tree_allclose(new, expected, rtol=1e-5)


def test_standard_adam_first_step_is_signlike(params, grads):
    """With bias correction the first update is ≈ -lr*sign(g)."""
    opt = Adam(lr=0.01)
    new, _ = opt.update(grads, opt.init(params), params)
    delta = jax.tree.map(lambda n, p: n - p, new, params)
    signs = jax.tree.map(lambda g: -0.01 * jnp.sign(g), grads)
    tree_allclose(delta, signs, rtol=1e-3)


def test_adam_variants_differ(params, grads):
    a, _ = Adam(lr=0.01).update(grads, Adam(lr=0.01).init(params), params)
    r, _ = ReferenceAdam(lr=0.01).update(
        grads, ReferenceAdam(lr=0.01).init(params), params
    )
    assert not np.allclose(a["w"], r["w"])


def test_update_is_jittable(params, grads):
    opt = Adam(lr=0.01)
    state = opt.init(params)
    jitted = jax.jit(opt.update)
    new, _ = jitted(grads, state, params)
    ref, _ = opt.update(grads, state, params)
    tree_allclose(new, ref, rtol=1e-6)


def test_factory():
    assert isinstance(make_optimizer("gd", 0.1), GradientDescent)
    assert isinstance(make_optimizer("sgd", 0.1, 0.9), Sgd)
    assert isinstance(make_optimizer("adam", 0.1), Adam)
    assert isinstance(make_optimizer("adam_ref", 0.1), ReferenceAdam)
    with pytest.raises(ValueError):
        make_optimizer("lbfgs", 0.1)


def test_optimizers_minimize_quadratic():
    """Every optimizer must drive ||x||² down."""
    for name in ("gd", "sgd", "adam", "adam_ref"):
        opt = make_optimizer(name, 0.05, momentum=0.9)
        x = {"x": jnp.array([3.0, -4.0])}
        state = opt.init(x)
        loss = lambda p: jnp.sum(p["x"] ** 2)
        l0 = loss(x)
        for _ in range(200):
            grads = jax.grad(loss)(x)
            x, state = opt.update(grads, state, x)
        assert loss(x) < 0.05 * l0, name
