"""Serving parity: the incremental prefill/decode path is the SAME
function as the full forward.

Load-bearing properties:

- greedy decode through the KV cache (chunked prefill + per-token
  ``apply_decode``) reproduces the full-forward logits at every emitted
  position to 1e-5/1e-6 — dense, GQA, learned-position-table, and
  TP-sharded configs;
- the quantized cache kinds match their ``_sim`` oracles EXACTLY (the
  decode-side dequant is bitwise the write-side roundtrip) and track the
  full-precision logits loosely;
- the cache primitives (per-slot token writes, chunk writes, prefix
  reads) are position-exact and donation-safe.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpudml.core.config import MeshConfig
from tpudml.core.dist import make_mesh
from tpudml.models import TransformerLM
from tpudml.serve import ServeConfig, ServingEngine, cache_bytes, init_cache
from tpudml.serve.cache import read_all, read_slot_prefix, write_chunk, write_token
from tpudml.serve.load import Request

V, D, HEADS, LAYERS, MAX_LEN = 48, 32, 4, 2, 32
RTOL, ATOL = 1e-5, 1e-6

CONFIGS = {
    "rope_dense": dict(rope=True),
    "rope_gqa": dict(rope=True, num_kv_heads=2),
    "pos_table": dict(rope=False),
}


def _model(**kw):
    base = dict(vocab_size=V, embed_dim=D, num_heads=HEADS,
                num_layers=LAYERS, max_len=MAX_LEN)
    base.update(kw)
    return TransformerLM(**base)


def _prompt(n=11, seed=3):
    return np.random.default_rng(seed).integers(0, V, n).astype(np.int32)


def incremental_logits(model, params, prompt, steps, *, kind="f32", chunk=4,
                       slots=1):
    """Greedy-decode ``steps`` tokens through the cache path (chunked
    prefill of prompt[:-1], then token-by-token apply_decode in slot 0);
    returns (logits list, emitted tokens)."""
    caches = model.init_decode_cache(slots, MAX_LEN, kind)
    p = len(prompt) - 1
    for s0 in range(0, p, chunk):
        buf = np.zeros((1, chunk), np.int32)
        n = min(chunk, p - s0)
        buf[0, :n] = prompt[s0:s0 + n]
        caches = model.apply_prefill(
            params, caches, jnp.asarray(buf), jnp.asarray(0, jnp.int32), s0)
    pos = np.full(slots, p, np.int32)
    last = np.full(slots, prompt[-1], np.int32)
    logits_seq, toks = [], []
    for _ in range(steps):
        logits, caches = model.apply_decode(
            params, caches, jnp.asarray(last), jnp.asarray(pos))
        logits_seq.append(np.asarray(logits[0]))
        t = int(jnp.argmax(logits[0]))
        toks.append(t)
        last = np.full(slots, t, np.int32)
        pos = pos + 1
    return logits_seq, toks


def full_forward_logits(model, params, prompt, steps):
    """Greedy reference: re-run the FULL forward per emitted token."""
    toks = list(prompt)
    logits_seq, out = [], []
    for _ in range(steps):
        logits, _ = model.apply(params, {}, jnp.asarray([toks], jnp.int32))
        row = np.asarray(logits[0, -1])
        logits_seq.append(row)
        t = int(np.argmax(row))
        toks.append(t)
        out.append(t)
    return logits_seq, out


# ------------------------------------------------- greedy logit parity


@pytest.mark.parametrize("cfg", list(CONFIGS), ids=list(CONFIGS))
def test_greedy_decode_logits_match_full_forward(cfg):
    model = _model(**CONFIGS[cfg])
    params, _ = model.init(jax.random.key(0))
    prompt = _prompt()
    inc, toks_inc = incremental_logits(model, params, prompt, steps=9)
    ref, toks_ref = full_forward_logits(model, params, prompt, steps=9)
    assert toks_inc == toks_ref
    for a, b in zip(inc, ref):
        np.testing.assert_allclose(a, b, rtol=RTOL, atol=ATOL)


@pytest.mark.parametrize("chunk", [1, 2, 4, 8])
def test_prefill_chunk_size_invariance(chunk):
    """Any chunking of the same prompt (including chunk=1 and a padded
    uneven tail) lands the same cache → identical decode logits."""
    model = _model(rope=True, num_kv_heads=2)
    params, _ = model.init(jax.random.key(1))
    prompt = _prompt(n=11, seed=5)  # 10 prefilled tokens: uneven vs 4/8
    ref, _ = full_forward_logits(model, params, prompt, steps=5)
    inc, _ = incremental_logits(model, params, prompt, steps=5, chunk=chunk)
    for a, b in zip(inc, ref):
        np.testing.assert_allclose(a, b, rtol=RTOL, atol=ATOL)


@pytest.mark.parametrize("kind,sim", [("bf16", "bf16_sim"),
                                      ("int8", "int8_sim")])
def test_quantized_cache_matches_sim_oracle(kind, sim):
    """The real quantized cache must equal its roundtrip-in-f32 twin
    BITWISE (dequant is deterministic), and track the full-precision
    logits loosely — the lossy-storage contract."""
    model = _model(rope=True, num_kv_heads=2)
    params, _ = model.init(jax.random.key(2))
    prompt = _prompt(seed=7)
    real, toks_real = incremental_logits(model, params, prompt, 7, kind=kind)
    oracle, toks_sim = incremental_logits(model, params, prompt, 7, kind=sim)
    assert toks_real == toks_sim
    for a, b in zip(real, oracle):
        np.testing.assert_allclose(a, b, rtol=RTOL, atol=ATOL)
    ref, _ = full_forward_logits(model, params, prompt, 7)
    for a, b in zip(real, ref):
        np.testing.assert_allclose(a, b, rtol=0, atol=0.25)


# ----------------------------------------------------------- TP parity


@pytest.mark.parametrize("cfg", ["rope_dense", "rope_gqa"])
def test_tp_decode_logits_match_full_forward(cfg):
    """The shard_map TP decode step (params via tensor_parallel_rules,
    cache sharded over kv_heads) is logit-exact against the unsharded
    full forward."""
    mesh = make_mesh(MeshConfig({"model": 2}), jax.devices()[:2])
    model = _model(**CONFIGS[cfg])
    params, _ = model.init(jax.random.key(3))
    prompt = _prompt(seed=9)
    scfg = ServeConfig(slots=2, max_len=MAX_LEN, prefill_chunk=4)
    eng = ServingEngine(model, params, scfg, mesh=mesh, axis_name="model")
    pos0, last0 = eng._admit(0, Request(rid=0, prompt=prompt,
                                        max_new_tokens=6))
    pos = np.array([pos0, 0], np.int32)
    last = np.array([last0, 0], np.int32)
    ref, toks_ref = full_forward_logits(model, params, prompt, steps=6)
    for i in range(6):
        next_t, logits, eng.caches = eng._decode(
            eng.params, eng.caches, jnp.asarray(last), jnp.asarray(pos))
        np.testing.assert_allclose(np.asarray(logits[0]), ref[i],
                                   rtol=RTOL, atol=ATOL)
        assert int(next_t[0]) == toks_ref[i]
        last = np.array([int(next_t[0]), 0], np.int32)
        pos = pos + np.array([1, 0], np.int32)


def test_tp_rejects_non_dividing_heads():
    mesh = make_mesh(MeshConfig({"model": 2}), jax.devices()[:2])
    model = _model(rope=True, num_heads=3, embed_dim=36, num_kv_heads=3)
    params, _ = model.init(jax.random.key(0))
    with pytest.raises(ValueError, match="divisible"):
        ServingEngine(model, params,
                      ServeConfig(slots=1, max_len=MAX_LEN, prefill_chunk=4),
                      mesh=mesh, axis_name="model")


# ------------------------------------------------------ cache primitives


def test_write_token_per_slot_positions():
    cache = init_cache(3, 8, 2, 4, "f32")
    k = jnp.arange(3 * 2 * 4, dtype=jnp.float32).reshape(3, 1, 2, 4)
    pos = jnp.asarray([0, 3, 7], jnp.int32)
    cache = write_token(cache, k, -k, pos)
    kk, vv = read_all(cache, jnp.float32)
    for b, p in enumerate([0, 3, 7]):
        np.testing.assert_array_equal(np.asarray(kk[b, p]),
                                      np.asarray(k[b, 0]))
        np.testing.assert_array_equal(np.asarray(vv[b, p]),
                                      np.asarray(-k[b, 0]))
        # every other row untouched
        mask = np.ones(8, bool)
        mask[p] = False
        assert np.all(np.asarray(kk[b])[mask] == 0)


def test_write_chunk_targets_one_slot():
    cache = init_cache(2, 8, 1, 2, "f32")
    k = jnp.ones((1, 4, 1, 2))
    cache = write_chunk(cache, k, 2 * k, jnp.asarray(1, jnp.int32), 4)
    kk, vv = read_all(cache, jnp.float32)
    assert np.all(np.asarray(kk[0]) == 0)  # slot 0 untouched
    assert np.all(np.asarray(kk[1, 4:8]) == 1)
    assert np.all(np.asarray(vv[1, 4:8]) == 2)
    assert np.all(np.asarray(kk[1, :4]) == 0)
    pk, _ = read_slot_prefix(cache, jnp.asarray(1, jnp.int32), 6, jnp.float32)
    assert pk.shape == (1, 6, 1, 2)
    assert np.all(np.asarray(pk[0, 4:6]) == 1)


def test_int8_cache_shrinks_storage():
    f32 = init_cache(2, 16, 2, 8, "f32")
    i8 = init_cache(2, 16, 2, 8, "int8")
    # 4 bytes -> 1 byte per element + f32 scales per (token, head)
    assert cache_bytes(i8) < cache_bytes(f32) / 2


def test_cache_buffers_are_donation_distinct():
    """k/v (and scales) must be separate buffers — the engine donates
    the cache pytree every step and XLA rejects double-donation."""
    cache = init_cache(1, 4, 1, 2, "int8")
    ptrs = {x.unsafe_buffer_pointer()
            for x in (cache.k, cache.v, cache.k_scale, cache.v_scale)}
    assert len(ptrs) == 4
