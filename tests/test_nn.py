"""Unit tests: nn layers and models (shapes, batchnorm state, LeNet parity
dims)."""

import jax
import jax.numpy as jnp
import numpy as np

from tpudml.models import ForwardMLP, LeNet, lenet_stages
from tpudml.nn import BatchNorm, Conv2D, Dense, Dropout, LayerNorm, MaxPool, Sequential


def test_dense_shapes():
    layer = Dense(4, 7)
    params, state = layer.init(jax.random.key(0))
    y, _ = layer.apply(params, state, jnp.ones((3, 4)))
    assert y.shape == (3, 7)


def test_conv_same_padding_preserves_hw():
    layer = Conv2D(1, 6, kernel_size=5, padding=2)
    params, _ = layer.init(jax.random.key(0))
    y, _ = layer.apply(params, {}, jnp.ones((2, 28, 28, 1)))
    assert y.shape == (2, 28, 28, 6)


def test_maxpool():
    y, _ = MaxPool(2).apply({}, {}, jnp.arange(16.0).reshape(1, 4, 4, 1))
    assert y.shape == (1, 2, 2, 1)
    np.testing.assert_allclose(np.asarray(y)[0, :, :, 0], [[5, 7], [13, 15]])


def test_lenet_forward_shapes():
    """Reference Net dims (codes/task1/pytorch/model.py:16-35): 28×28 input
    → 400-dim flatten → 120 → 10."""
    model = LeNet()
    params, state = model.init(jax.random.key(0))
    x = jnp.ones((5, 28, 28, 1))
    y, _ = model.apply(params, state, x)
    assert y.shape == (5, 10)
    # fc1 kernel must be 400x120 (16*5*5 flatten)
    assert params["layer7"]["kernel"].shape == (400, 120)


def test_mlp_forward():
    model = ForwardMLP()
    params, state = model.init(jax.random.key(0))
    y, _ = model.apply(params, state, jnp.ones((2, 28, 28, 1)))
    assert y.shape == (2, 10)


def test_staged_equals_composition():
    """Staged LeNet must compute the same function shape-wise and run
    stage-by-stage."""
    model = lenet_stages()
    params, state = model.init(jax.random.key(1))
    x = jnp.ones((4, 28, 28, 1))
    y, _ = model.apply(params, state, x)
    assert y.shape == (4, 10)
    assert model.stage_names() == ["conv", "fc"]


def test_batchnorm_updates_state_in_train():
    bn = BatchNorm(3, momentum=0.5)
    params, state = bn.init(jax.random.key(0))
    x = jnp.ones((8, 3)) * 4.0
    y, new_state = bn.apply(params, state, x, train=True)
    np.testing.assert_allclose(np.asarray(new_state["mean"]), [2.0] * 3, rtol=1e-6)
    y_eval, same_state = bn.apply(params, state, x, train=False)
    assert same_state is state


def test_batchnorm_f32_large_mean_recovers_variance():
    """Two-pass variance for f32 inputs: at mean 1e4 with std 0.1 the
    single-pass E[x²]−m² form loses every variance bit to f32
    cancellation (clamp → var=0, output blown up by rsqrt(eps)); the
    two-pass E[(x−m)²] recovers it. bf16 inputs keep the cheaper
    single-pass form — their quantization floor is above the
    cancellation error anyway."""
    bn = BatchNorm(16)
    params, state = bn.init(jax.random.key(0))
    noise = jax.random.normal(jax.random.key(1), (512, 16))
    x = 1e4 + 0.1 * noise
    y, new_state = bn.apply(params, state, x, train=True)
    # momentum 0.9 folds 0.1 of the batch var (~0.01) into state var 1.0
    batch_var = (np.asarray(new_state["var"]) - 0.9) / 0.1
    np.testing.assert_allclose(batch_var, 0.01, rtol=0.2)
    # normalized output ≈ the (unit-ish) noise, not rsqrt(eps)-scaled
    assert float(jnp.std(y)) < 3.0
    # bf16 path still runs and stays finite through its single-pass form
    y16, _ = bn.apply(params, state, x.astype(jnp.bfloat16), train=True)
    assert np.all(np.isfinite(np.asarray(y16, dtype=np.float32)))


def test_dropout_train_vs_eval():
    d = Dropout(0.5)
    x = jnp.ones((100, 100))
    y_eval, _ = d.apply({}, {}, x, train=False)
    np.testing.assert_array_equal(np.asarray(y_eval), np.asarray(x))
    y_train, _ = d.apply({}, {}, x, train=True, rng=jax.random.key(0))
    frac_zero = float(jnp.mean((y_train == 0).astype(jnp.float32)))
    assert 0.4 < frac_zero < 0.6


def test_sequential_threads_rng_and_state():
    model = Sequential(layers=(Dense(4, 4), Dropout(0.5), BatchNorm(4)))
    params, state = model.init(jax.random.key(0))
    y, new_state = model.apply(
        params, state, jnp.ones((2, 4)), train=True, rng=jax.random.key(1)
    )
    assert "layer2" in new_state


def test_layernorm_large_mean_rows_stay_finite():
    """Single-pass moments (E[x²]−m²) cancel catastrophically in f32 when
    m² >> var; the clamp must keep rsqrt finite (review r3 finding)."""
    import jax

    ln = LayerNorm(512)
    p, _ = ln.init(jax.random.PRNGKey(0))
    x = jnp.full((4, 512), 300.0) + 1e-3 * jax.random.normal(
        jax.random.PRNGKey(1), (4, 512)
    )
    y, _ = ln.apply(p, {}, x)
    assert np.all(np.isfinite(np.asarray(y)))


def test_embed_lookup_matmul_backward_matches_scatter():
    """embed_lookup's one-hot-matmul backward must equal autodiff's
    scatter-add gradient exactly (same per-row cotangent sums), including
    repeated tokens — the correctness contract behind swapping TPU
    scatter (3.6 ms) for an MXU matmul (1.0 ms) at the flagship shapes."""
    import jax

    from tpudml.models.transformer import embed_lookup

    E = jax.random.normal(jax.random.PRNGKey(0), (32, 8))
    # Force repeats so multiple cotangent rows sum into one table row.
    toks = jnp.asarray([[1, 1, 5, 31], [0, 1, 5, 5]], jnp.int32)
    g = jax.random.normal(jax.random.PRNGKey(2), (2, 4, 8))

    got = jax.grad(lambda E: jnp.sum(embed_lookup(E, toks) * g))(E)
    want = jax.grad(lambda E: jnp.sum(E[toks] * g))(E)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-6)
    # Forward is the plain gather.
    np.testing.assert_array_equal(
        np.asarray(embed_lookup(E, toks)), np.asarray(E[toks])
    )
