"""Flight-recorder tests: tracer determinism, in-graph StepStats,
serve-trace parity, the zero-cost disabled path, and the drift gate.

Load-bearing properties:

- a fixed event log exports a byte-identical Chrome trace (golden file
  under ``tests/obs_fixtures/``), and the document passes both our own
  schema validator and the sort/nesting contract trace viewers require;
- the serving trace is a PURE function of the engine's deterministic
  event log: two identical paged+spec 2×-overload runs (the PR 11 golden
  config) write byte-identical ``trace.json`` files;
- ``obs=False`` (the default) allocates ZERO ``Span`` objects across a
  full train step — pinned via the module's ``SPANS_ALLOCATED`` counter,
  not a benchmark;
- DP's in-graph ``StepStats`` agrees with ground truth: loss matches the
  metrics dict, the split-step comm-bytes leaf reproduces the measured
  ``CommStats`` accounting exactly, and the leaf grows linearly in step;
- ``metrics.jsonl`` stays strict JSON through NaN/Inf losses;
- ``python -m tpudml.obs --check-drift`` exits 0 on the live world-4
  regimes (static-vs-measured agreement, the PR 10 pin held
  continuously) and non-zero on a seeded mismatch fixture.
"""

import json
from pathlib import Path

import jax
import numpy as np
import pytest

from tpudml.core.config import MeshConfig
from tpudml.core.dist import make_mesh
from tpudml.core.prng import seed_key
from tpudml.data.datasets import synthetic_classification
from tpudml.metrics import MetricsWriter
from tpudml.metrics.profiler import SpanTimer
from tpudml.models import LeNet, TransformerLM
from tpudml.obs import (
    TRACE_SCHEMA_VERSION,
    Tracer,
    dump_trace,
    get_tracer,
    serve_trace_events,
    use_tracer,
    validate_chrome_trace,
    write_serve_trace,
)
from tpudml.obs import tracer as tracer_mod
from tpudml.optim import make_optimizer
from tpudml.parallel.dp import DataParallel
from tpudml.serve import ServeConfig, ServingEngine, poisson_workload

FIXTURES = Path(__file__).parent / "obs_fixtures"
WORLD = 8


@pytest.fixture(scope="module")
def mesh():
    return make_mesh(MeshConfig({"data": WORLD}))


@pytest.fixture(scope="module")
def batch():
    images, labels = synthetic_classification(WORLD * 4, (28, 28, 1), 10, seed=7)
    return np.asarray(images), np.asarray(labels)


# ------------------------------------------------------------ tracer core


def golden_tracer() -> Tracer:
    """The fixed event log behind ``obs_fixtures/golden_trace.json`` —
    one span per feed source, explicit timestamps (no wall clock)."""
    tr = Tracer(clock=lambda: 0.0)
    tr.add_complete("train_step", cat="step", ts_us=0, dur_us=1500, tid=0)
    tr.add_complete("psum", cat="comm", ts_us=100, dur_us=300, tid=0,
                    args={"bytes": 4096})
    tr.add_complete("checkpoint_save", cat="checkpoint", ts_us=1600,
                    dur_us=400, tid=1, args={"step": 3})
    tr.instant("sentinel_trip", cat="sentinel", ts_us=900,
               args={"step": 2, "consecutive": 1})
    tr.instant("launch_restart", cat="launch", ts_us=2100,
               args={"attempt": 1, "why": "exit 1"})
    return tr


def test_chrome_trace_matches_golden_bytes():
    """Byte-for-byte against the checked-in fixture: any change to the
    sort order, key set, or serialization is a schema change and must
    bump TRACE_SCHEMA_VERSION + regenerate the golden."""
    got = dump_trace(golden_tracer().chrome_trace(pid=0)).encode()
    want = (FIXTURES / "golden_trace.json").read_bytes()
    assert got == want


def test_chrome_trace_validates_and_sorts():
    doc = golden_tracer().chrome_trace(pid=0)
    validate_chrome_trace(doc)
    events = [e for e in doc["traceEvents"] if e["ph"] != "M"]
    # Deterministic order: ts ascending, parents (longer dur) first.
    keys = [(e["ts"], -e.get("dur", 0), e["tid"]) for e in events]
    assert keys == sorted(keys)
    assert events[0]["name"] == "train_step"  # contains the comm span
    assert doc["metadata"]["tpudml_trace_schema"] == TRACE_SCHEMA_VERSION


def test_validate_rejects_malformed():
    with pytest.raises(ValueError, match="traceEvents"):
        validate_chrome_trace([])
    with pytest.raises(ValueError, match="schema"):
        validate_chrome_trace({"traceEvents": [], "metadata": {}})
    doc = golden_tracer().chrome_trace(pid=0)
    doc["traceEvents"][1]["ts"] = 0.5  # float timestamps break Perfetto
    with pytest.raises(ValueError, match="int ts"):
        validate_chrome_trace(doc)


def test_merge_chrome_traces_one_pid_track_per_process():
    """Multi-process evidence path (tpudml.elastic drill): per-rank
    exports merge into one document with one pid track per process,
    deterministically ordered, and a pid collision is a loud error."""
    from tpudml.obs import merge_chrome_traces

    docs = [golden_tracer().chrome_trace(pid=p) for p in (1, 0)]
    merged = merge_chrome_traces(docs)
    validate_chrome_trace(merged)
    metas = [e for e in merged["traceEvents"] if e["ph"] == "M"]
    events = [e for e in merged["traceEvents"] if e["ph"] != "M"]
    assert [m["pid"] for m in metas] == [0, 1]
    assert {e["pid"] for e in events} == {0, 1}
    keys = [(e["pid"], e["ts"], -e.get("dur", 0)) for e in events]
    assert keys == sorted(keys)
    # Byte-deterministic regardless of input order.
    assert dump_trace(merged) == dump_trace(merge_chrome_traces(docs[::-1]))
    with pytest.raises(ValueError, match="duplicate pid"):
        merge_chrome_traces([docs[0], docs[0]])


def test_tracer_summary_percentiles():
    s = golden_tracer().summary()
    assert s["schema"] == TRACE_SCHEMA_VERSION
    st = s["spans"]["step/train_step"]
    assert st["count"] == 1 and st["total_us"] == 1500
    assert st["p50_us"] == 1500 and st["p99_us"] == 1500
    assert set(s["spans"]) == {
        "step/train_step", "comm/psum", "checkpoint/checkpoint_save",
        "sentinel/sentinel_trip", "launch/launch_restart",
    }


def test_ambient_tracer_scoping():
    assert get_tracer() is tracer_mod.NULL_TRACER
    tr = Tracer()
    with use_tracer(tr):
        assert get_tracer() is tr
        with get_tracer().span("inner", cat="test"):
            pass
    assert get_tracer() is tracer_mod.NULL_TRACER
    assert [s.name for s in tr.events] == ["inner"]


def test_span_timer_feeds_tracer_and_percentiles():
    tr = Tracer()
    t = SpanTimer(tracer=tr)
    for _ in range(3):
        with t.span("step"):
            pass
    pct = t.percentiles("step")
    assert set(pct) >= {"p50_s", "p99_s"} and pct["p50_s"] <= pct["p99_s"]
    rpt = t.report()
    # The PR's report additions keep the long-standing pins intact.
    assert "step: " in rpt and "3 calls" in rpt
    assert "p50 " in rpt and "p99 " in rpt
    assert [(s.cat, s.name) for s in tr.events] == [("timer", "step")] * 3


# -------------------------------------------------------- metrics writer


def test_metrics_jsonl_stays_strict_json_through_nonfinite(tmp_path):
    w = MetricsWriter(tmp_path, run_name="nf")
    w.add_scalar("Train Loss", 1.25, 0)
    w.add_scalar("Train Loss", float("nan"), 1)
    w.add_scalar("Train Loss", float("inf"), 2)
    w.add_scalars({"obs/grad_norm": float("-inf"), "obs/loss": 0.5}, 3)
    w.close()
    lines = (w.run_dir / "metrics.jsonl").read_text().splitlines()
    recs = [json.loads(line) for line in lines]  # every line strict JSON
    assert recs[0]["value"] == 1.25 and "finite" not in recs[0]
    for r in recs[1:3]:
        assert r["value"] is None and r["finite"] is False
    assert recs[3]["tag"] == "obs/grad_norm" and recs[3]["value"] is None
    assert recs[4] == {k: recs[4][k] for k in ("tag", "value", "step",
                                               "wall_time")}


# ------------------------------------------------- serve trace conversion


def test_serve_trace_events_pure_conversion():
    events = [
        ("admit", 7, 0, 0),
        ("spec", 7, 0, 2, 1),
        ("reject", 9, -1, 3),
        ("evict", 7, 0, 5),
        ("admit", 8, 0, 6),  # still resident at log end
    ]
    evs = serve_trace_events(events, step_time_s=0.01)
    spans = [e for e in evs if e["ph"] == "X"]
    instants = [e for e in evs if e["ph"] == "i"]
    assert len(instants) == 5
    assert {(s["name"], s["ts"], s["dur"], s["tid"]) for s in spans} == {
        ("slot0:rid7", 0, 50_000, 1),
        ("slot0:rid8", 60_000, 0, 1),  # closed at max_step
    }
    reject = next(e for e in instants if e["name"] == "reject")
    assert reject["tid"] == 0 and reject["ts"] == 30_000
    # Pure function: same events in, same events out.
    assert serve_trace_events(events, step_time_s=0.01) == evs


def test_paged_spec_overload_trace_is_byte_deterministic(tmp_path):
    """PR 11's golden config (paged + spec + bounded queue at 2× overload
    on the virtual clock): two identical runs must write byte-identical
    trace.json files, with queue, slot-residency, and spec events all
    present."""
    model = TransformerLM(vocab_size=48, embed_dim=32, num_heads=4,
                          num_layers=2, num_kv_heads=2, max_len=32,
                          rope=True)
    params, _ = model.init(jax.random.key(6))
    cfg = ServeConfig(slots=1, max_len=32, prefill_chunk=4,
                      cache_layout="paged", page_size=4, spec_k=2,
                      max_queue=2, step_time_s=0.01)

    def once(tag):
        reqs, _ = poisson_workload(10, 40.0, seed=5, vocab_size=48,
                                   prompt_len=(2, 6), new_tokens=(8, 8))
        report = ServingEngine(model, params, cfg, draft_layers=1).run(reqs)
        path = write_serve_trace(report, tmp_path / tag / "trace.json",
                                 step_time_s=0.01, pid=0)
        return report, path.read_bytes()

    report, a = once("a")
    _, b = once("b")
    assert a == b

    doc = json.loads(a)
    validate_chrome_trace(doc)
    kinds = {e["name"] for e in doc["traceEvents"] if e["ph"] == "i"}
    assert {"admit", "spec", "reject"} <= kinds  # overload guard engaged
    residency = [e for e in doc["traceEvents"]
                 if e["ph"] == "X" and e["name"].startswith("slot")]
    assert residency and all(e["tid"] >= 1 for e in residency)
    assert any(e["tid"] == 0 for e in doc["traceEvents"]
               if e.get("name") == "reject")
    assert report.rejected > 0


# --------------------------------------------- engine knob: off = free


def test_obs_off_allocates_zero_spans(mesh, batch):
    dp = DataParallel(LeNet(), make_optimizer("sgd", 0.01), mesh)
    ts = dp.create_state(seed_key(0))
    step = dp.make_train_step()
    before = tracer_mod.SPANS_ALLOCATED
    for _ in range(2):
        ts, m = step(ts, *batch)
    jax.block_until_ready(m["loss"])
    assert tracer_mod.SPANS_ALLOCATED == before
    assert "step_stats" not in m


def test_obs_on_records_spans_and_stepstats(mesh, batch):
    tr = Tracer()
    dp = DataParallel(LeNet(), make_optimizer("sgd", 0.01), mesh, obs=tr)
    ts = dp.create_state(seed_key(0))
    step = dp.make_train_step()
    ts, m0 = step(ts, *batch)
    ts, m1 = step(ts, *batch)
    assert [(s.cat, s.name) for s in tr.events] == [("step", "train_step")] * 2

    stats = m1["step_stats"]
    scal = {k: float(v) for k, v in stats.to_scalars().items()}
    assert scal["loss"] == pytest.approx(float(m1["loss"]), rel=1e-6)
    assert scal["grad_norm"] > 0
    assert scal["sentinel_skips"] == 0 and scal["sentinel_consecutive"] == 0
    # comm_bytes is (per-step ring-model constant) × (step+1).
    b0 = float(m0["step_stats"].comm_bytes)
    assert b0 > 0 and scal["comm_bytes"] == pytest.approx(2 * b0, rel=1e-6)


def test_split_step_stats_match_measured_comm(mesh, batch):
    """The in-graph comm-bytes leaf is priced on the same ring model as
    the measured path, so one split step's StepStats reproduces the
    CommStats byte accounting exactly."""
    dp = DataParallel(LeNet(), make_optimizer("sgd", 0.01), mesh,
                      measure_comm=True, obs=True)
    ts = dp.create_state(seed_key(0))
    ts, m = dp.make_train_step()(ts, *batch)
    got = float(m["step_stats"].comm_bytes)
    assert got == pytest.approx(dp.comm_stats.comm_bytes, rel=1e-9)
    # measure_comm feeds the tracer too: comm spans carry byte args.
    comm = [s for s in dp.tracer.events if s.cat == "comm"]
    assert comm and all(s.args and s.args.get("bytes", 0) > 0 for s in comm)


# ------------------------------------------------------------ drift gate


def test_drift_cli_live_regimes_within_threshold(tmp_path, capsys):
    """The CI gate on the live world-4 regimes (DP/SGD, ZeRO-1/Adam):
    static cost reports agree with measured CommStats within 10%."""
    from tpudml.obs.__main__ import main

    out = tmp_path / "drift.json"
    rc = main(["--check-drift", "--out", str(out), "--format", "json"])
    assert rc == 0
    report = json.loads(capsys.readouterr().out)
    assert report["ok"] and report["worst_rel_err"] <= 0.10
    assert {r["entrypoint"] for r in report["records"]} == {
        "task2_dp", "dp_zero1"}
    assert json.loads(out.read_text())["records"] == report["records"]


def test_drift_cli_gates_on_seeded_mismatch(tmp_path, capsys):
    from tpudml.obs.__main__ import main

    fixture = tmp_path / "pairs.json"
    fixture.write_text(json.dumps([
        {"entrypoint": "task2_dp", "static_wire_bytes": 100.0,
         "measured_wire_bytes": 200.0},
        {"entrypoint": "dp_zero1", "static_wire_bytes": 100.0,
         "measured_wire_bytes": 101.0},
    ]))
    out = tmp_path / "drift.json"
    rc = main(["--check-drift", "--fixture", str(fixture),
               "--out", str(out), "--format", "github"])
    assert rc == 1
    lines = capsys.readouterr().out.splitlines()
    assert len(lines) == 1 and lines[0].startswith("::warning ")
    assert "task2_dp" in lines[0] and "50.00%" in lines[0]
    report = json.loads(out.read_text())
    assert not report["ok"]
    assert [r["status"] for r in report["records"]] == ["WARN", "OK"]

    # Report-only mode never gates.
    assert main(["--fixture", str(fixture), "--out", str(out)]) == 0
    capsys.readouterr()


# ------------------------------------------------------------ obs_report


def test_obs_report_summarizes_run_dir(tmp_path, capsys):
    from tools.obs_report import main, report
    from tpudml.obs.drift import (
        build_drift_report,
        drift_from_pairs,
        write_drift_report,
    )

    w = MetricsWriter(tmp_path, run_name="rpt")
    w.add_scalar("Train Loss", 2.3, 0)
    w.add_scalar("Train Loss", float("nan"), 1)
    w.close()
    run_dir = w.run_dir
    golden_tracer().export(run_dir / "trace.json", pid=0)
    write_drift_report(
        build_drift_report(drift_from_pairs([
            {"entrypoint": "task2_dp", "static_wire_bytes": 100.0,
             "measured_wire_bytes": 100.0}])),
        str(run_dir / "obs" / "drift.json"))

    text = report(run_dir)
    assert "Train Loss" in text and "non-finite" in text
    assert "step/train_step" in text and "comm/psum" in text
    assert "task2_dp" in text and "OK" in text

    assert main([str(run_dir)]) == 0
    assert "metrics.jsonl" in capsys.readouterr().out
    assert main([str(run_dir / "nope")]) == 2
