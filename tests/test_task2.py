"""Integration: task2 end-to-end on the simulated 8-device mesh —
DP training converges, both aggregation strategies work, comm-time and
bottleneck accounting are produced (SURVEY.md §4 integration tier)."""

import pytest

import tasks.task2 as task2
from tpudml.core.config import TrainConfig


def small_cfg(tmp_path, **overrides) -> TrainConfig:
    cfg = task2.reference_defaults()
    cfg.epochs = 2
    cfg.lr = 0.05  # synthetic smoke run: converge within 2 short epochs
    cfg.log_every = 50
    cfg.log_dir = str(tmp_path / "logs")
    cfg.data.dataset = "synthetic"
    cfg.data.batch_size = 8  # per-replica
    for k, v in overrides.items():
        setattr(cfg, k, v)
    return cfg


@pytest.mark.parametrize("aggregation", ["allreduce", "allgather"])
def test_task2_end_to_end(tmp_path, aggregation):
    cfg = small_cfg(tmp_path, aggregation=aggregation)
    metrics = task2.run(cfg)
    assert metrics["world"] == 8
    assert metrics["test_accuracy"] > 0.5
    assert metrics["loss"] < 2.3


def test_task2_n_devices_1_is_single_machine_baseline(tmp_path):
    """--n_devices 1 must run on ONE device (task3.tex:23's single-machine
    comparison), not silently use the whole mesh."""
    cfg = small_cfg(tmp_path)
    cfg.epochs = 1
    cfg.data.batch_size = 64
    cfg.dist.num_processes = 1
    cfg.dist.explicit_world = True
    metrics = task2.run(cfg)
    assert metrics["world"] == 1


def test_task2_measure_comm_and_bottleneck(tmp_path):
    cfg = small_cfg(tmp_path, measure_comm=True, bottleneck_rank=0)
    cfg.bottleneck_delay_s = 0.01
    metrics = task2.run(cfg)
    assert metrics["comm_time_s"] > 0.0
    assert metrics["test_accuracy"] > 0.4
