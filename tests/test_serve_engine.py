"""Continuous-batching scheduler contract.

Load-bearing properties: a fixed workload seed reproduces the exact
eviction/refill event sequence and token streams (determinism), every
queued request completes with exactly the token count the load
generator's ledger owes it (accounting, no starvation), slots are
actually reused mid-flight (continuous batching, not drain-and-refill),
and the config validators reject the shapes that would silently corrupt
a cache.
"""

import math

import jax
import numpy as np
import pytest

from tpudml.models import TransformerLM
from tpudml.serve import (
    Request,
    ServeConfig,
    ServingEngine,
    poisson_workload,
)

V = 48


def _model():
    return TransformerLM(vocab_size=V, embed_dim=32, num_heads=4,
                         num_layers=2, max_len=64, rope=True,
                         num_kv_heads=2)


@pytest.fixture(scope="module")
def setup():
    model = _model()
    params, _ = model.init(jax.random.key(0))
    return model, params


def _run(model, params, n=10, seed=11, slots=3, **wl):
    cfg = ServeConfig(slots=slots, max_len=64, prefill_chunk=8)
    eng = ServingEngine(model, params, cfg)
    base = dict(vocab_size=V, prompt_len=(2, 12), new_tokens=(3, 8))
    base.update(wl)
    reqs, ledger = poisson_workload(n, math.inf, seed, **base)
    return eng.run(reqs), ledger


def test_every_request_completes_with_owed_tokens(setup):
    """No starvation, exact accounting: 10 requests through 3 slots all
    finish with precisely ledger[rid]['max_new_tokens'] tokens."""
    rep, ledger = _run(*setup)
    assert set(rep.requests) == set(ledger)
    for rid, owed in ledger.items():
        st = rep.requests[rid]
        assert st.finished is not None, f"request {rid} starved"
        assert len(st.tokens) == owed["max_new_tokens"]
        assert len(st.token_times) == len(st.tokens)
        assert st.prompt_len == owed["prompt_len"]
        assert st.admitted is not None and st.first_token is not None
        assert st.arrival <= st.admitted <= st.first_token <= st.finished
    assert rep.generated_tokens == sum(
        o["max_new_tokens"] for o in ledger.values())


def test_event_log_is_deterministic(setup):
    model, params = setup
    rep1, _ = _run(model, params)
    rep2, _ = _run(model, params)
    assert rep1.events == rep2.events
    assert rep1.decode_steps == rep2.decode_steps
    for rid in rep1.requests:
        assert rep1.requests[rid].tokens == rep2.requests[rid].tokens
        assert rep1.requests[rid].slot == rep2.requests[rid].slot


def test_slots_are_refilled_mid_flight(setup):
    """Continuous batching: with more requests than slots, some admit
    happens at a decode step > 0 (a freed slot re-enters the batch while
    other slots are mid-generation), every admit/evict pairs up, and a
    slot never holds two live requests."""
    rep, _ = _run(*setup)
    admits = [e for e in rep.events if e[0] == "admit"]
    assert any(e[3] > 0 for e in admits), "no mid-flight refill happened"
    live = {}
    for kind, rid, slot, _step in rep.events:
        if kind == "admit":
            assert slot not in live, f"slot {slot} double-occupied"
            live[slot] = rid
        else:
            assert live.pop(slot) == rid
    assert not live


def test_fifo_admission_order(setup):
    """With all arrivals at t=0, admission order is request id order
    (FIFO with rid tie-break) — the queue head is never bypassed."""
    rep, _ = _run(*setup)
    admitted = [e[1] for e in rep.events if e[0] == "admit"]
    assert admitted == sorted(admitted)


def test_eos_token_stops_early(setup):
    """Re-running with eos_token set to a token the greedy stream is
    known (from a reference run) to emit cuts that request short."""
    model, params = setup
    ref, _ = _run(model, params, n=4, seed=5)
    rid, st = next((r, s) for r, s in ref.requests.items()
                   if len(s.tokens) >= 2)
    eos = st.tokens[0]
    cfg = ServeConfig(slots=3, max_len=64, prefill_chunk=8, eos_token=eos)
    eng = ServingEngine(model, params, cfg)
    reqs, _ = poisson_workload(4, math.inf, 5, vocab_size=V,
                               prompt_len=(2, 12), new_tokens=(3, 8))
    rep = eng.run(reqs)
    st2 = rep.requests[rid]
    assert len(st2.tokens) == 1 and st2.tokens[0] == eos
    for s in rep.requests.values():  # every stream stops at eos or budget
        assert s.tokens[-1] == eos or len(s.tokens) == len(
            ref.requests[s.rid].tokens)


def test_latency_summary_and_throughput(setup):
    rep, _ = _run(*setup, n=5)
    lat = rep.latency_summary()
    for key in ("per_token_p50_s", "per_token_p99_s", "e2e_p50_s",
                "e2e_p99_s", "ttft_p50_s", "ttft_p99_s"):
        assert np.isfinite(lat[key]) and lat[key] >= 0
    assert lat["per_token_p50_s"] <= lat["per_token_p99_s"]
    assert rep.tokens_per_sec > 0
    assert rep.wall_time > 0


def test_oversized_request_rejected(setup):
    model, params = setup
    eng = ServingEngine(model, params,
                        ServeConfig(slots=1, max_len=64, prefill_chunk=8))
    big = Request(rid=0, prompt=np.zeros(60, np.int32), max_new_tokens=10)
    with pytest.raises(ValueError, match="exceeds cache max_len"):
        eng.run([big])


def test_config_validation():
    with pytest.raises(ValueError, match="divide"):
        ServeConfig(slots=2, max_len=64, prefill_chunk=7)
    with pytest.raises(ValueError, match="cache_kind"):
        ServeConfig(cache_kind="fp4")
    with pytest.raises(ValueError, match="slots"):
        ServeConfig(slots=0)


def test_workload_generator_contract():
    reqs, ledger = poisson_workload(6, 2.0, 3, vocab_size=V,
                                    prompt_len=(1, 4), new_tokens=(2, 5))
    arrivals = [r.arrival_time for r in reqs]
    assert arrivals == sorted(arrivals) and arrivals[0] > 0
    reqs2, _ = poisson_workload(6, 2.0, 3, vocab_size=V,
                                prompt_len=(1, 4), new_tokens=(2, 5))
    for a, b in zip(reqs, reqs2):  # same seed → identical stream
        assert a.arrival_time == b.arrival_time
        assert np.array_equal(a.prompt, b.prompt)
        assert a.max_new_tokens == b.max_new_tokens
    for r in reqs:
        assert 1 <= len(r.prompt) <= 4
        assert 2 <= r.max_new_tokens <= 5
        assert ledger[r.rid]["prompt_len"] == len(r.prompt)


# ------------------------------------------------------- overload guard


def _req(rid, plen, owed, t=0.0):
    return Request(rid=rid, prompt=(np.arange(plen, dtype=np.int32) % V),
                   max_new_tokens=owed, arrival_time=t)


def _terminal_states(rep):
    """Every request must end in EXACTLY one terminal state."""
    out = {}
    for rid, s in rep.requests.items():
        states = [name for name, v in
                  (("finished", s.finished), ("rejected", s.rejected),
                   ("expired", s.expired)) if v is not None]
        assert len(states) == 1, (rid, states)
        out[rid] = states[0]
    return out


def test_overload_bounded_queue_no_starvation(setup):
    """2x-overload soak: the waiting line never exceeds max_queue (the
    excess is rejected at admission control, not silently buffered), no
    admitted request starves (FIFO order preserved), and every rid lands
    in exactly one terminal state with its full owed tokens if it
    finished."""
    model, params = setup
    cfg = ServeConfig(slots=1, max_len=64, prefill_chunk=8,
                      max_queue=3, step_time_s=0.01)
    eng = ServingEngine(model, params, cfg)
    reqs, ledger = poisson_workload(
        12, 40.0, seed=5, vocab_size=V, prompt_len=(2, 6),
        new_tokens=(8, 8))  # service ~12.5 req/s vs 40 qps offered
    rep = eng.run(reqs)

    states = _terminal_states(rep)
    assert rep.rejected > 0  # the guard actually engaged
    assert rep.peak_queue_depth <= cfg.max_queue
    admitted = [e[1] for e in rep.events if e[0] == "admit"]
    assert admitted == sorted(admitted)  # FIFO: arrival order == admit order
    for rid, state in states.items():
        if state == "finished":
            assert len(rep.requests[rid].tokens) == ledger[rid]["max_new_tokens"]
        else:
            assert state == "rejected"  # no deadline configured
    # Reject events carry slot -1 (never admitted).
    assert all(e[2] == -1 for e in rep.events if e[0] == "reject")


def test_deadline_expires_queued_and_midflight(setup):
    """One TTL, both expiry paths: the queued request dies waiting for
    the only slot (slot -1 in the event), the admitted one dies at a
    step boundary mid-generation (its slot id in the event) keeping its
    partial tokens in the ledger."""
    model, params = setup
    cfg = ServeConfig(slots=1, max_len=64, prefill_chunk=8,
                      deadline_s=0.2, step_time_s=0.01)
    eng = ServingEngine(model, params, cfg)
    rep = eng.run([_req(0, 4, 50), _req(1, 4, 4)])

    states = _terminal_states(rep)
    assert states == {0: "expired", 1: "expired"}
    r0, r1 = rep.requests[0], rep.requests[1]
    assert 0 < len(r0.tokens) < 50  # mid-flight: partial generation kept
    assert r0.finished is None
    assert len(r1.tokens) == 0  # starved in the queue, never admitted
    kinds = {e[1]: e for e in rep.events if e[0] == "expire"}
    assert kinds[0][2] == 0  # r0 expired IN its slot
    assert kinds[1][2] == -1  # r1 expired in the queue


def test_overload_run_is_deterministic(setup):
    """Same seed, same config -> byte-identical event log and ledger
    (the virtual step clock removes wall time from scheduling)."""
    model, params = setup
    cfg = ServeConfig(slots=2, max_len=64, prefill_chunk=8, max_queue=2,
                      deadline_s=0.5, step_time_s=0.01)

    def once():
        reqs, _ = poisson_workload(10, 30.0, seed=7, vocab_size=V,
                                   prompt_len=(2, 8), new_tokens=(4, 9))
        return ServingEngine(model, params, cfg).run(reqs)

    a, b = once(), once()
    assert a.events == b.events
    assert a.peak_queue_depth == b.peak_queue_depth
    assert _terminal_states(a) == _terminal_states(b)
    for rid in a.requests:
        assert a.requests[rid].tokens == b.requests[rid].tokens


def test_overload_config_validation():
    with pytest.raises(ValueError, match="max_queue"):
        ServeConfig(max_queue=0)
    with pytest.raises(ValueError, match="deadline_s"):
        ServeConfig(deadline_s=0.0)
    with pytest.raises(ValueError, match="step_time_s"):
        ServeConfig(step_time_s=-1.0)
