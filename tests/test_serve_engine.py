"""Continuous-batching scheduler contract.

Load-bearing properties: a fixed workload seed reproduces the exact
eviction/refill event sequence and token streams (determinism), every
queued request completes with exactly the token count the load
generator's ledger owes it (accounting, no starvation), slots are
actually reused mid-flight (continuous batching, not drain-and-refill),
and the config validators reject the shapes that would silently corrupt
a cache.
"""

import math

import jax
import numpy as np
import pytest

from tpudml.models import TransformerLM
from tpudml.serve import (
    Request,
    ServeConfig,
    ServingEngine,
    poisson_workload,
)

V = 48


def _model():
    return TransformerLM(vocab_size=V, embed_dim=32, num_heads=4,
                         num_layers=2, max_len=64, rope=True,
                         num_kv_heads=2)


@pytest.fixture(scope="module")
def setup():
    model = _model()
    params, _ = model.init(jax.random.key(0))
    return model, params


def _run(model, params, n=10, seed=11, slots=3, **wl):
    cfg = ServeConfig(slots=slots, max_len=64, prefill_chunk=8)
    eng = ServingEngine(model, params, cfg)
    base = dict(vocab_size=V, prompt_len=(2, 12), new_tokens=(3, 8))
    base.update(wl)
    reqs, ledger = poisson_workload(n, math.inf, seed, **base)
    return eng.run(reqs), ledger


def test_every_request_completes_with_owed_tokens(setup):
    """No starvation, exact accounting: 10 requests through 3 slots all
    finish with precisely ledger[rid]['max_new_tokens'] tokens."""
    rep, ledger = _run(*setup)
    assert set(rep.requests) == set(ledger)
    for rid, owed in ledger.items():
        st = rep.requests[rid]
        assert st.finished is not None, f"request {rid} starved"
        assert len(st.tokens) == owed["max_new_tokens"]
        assert len(st.token_times) == len(st.tokens)
        assert st.prompt_len == owed["prompt_len"]
        assert st.admitted is not None and st.first_token is not None
        assert st.arrival <= st.admitted <= st.first_token <= st.finished
    assert rep.generated_tokens == sum(
        o["max_new_tokens"] for o in ledger.values())


def test_event_log_is_deterministic(setup):
    model, params = setup
    rep1, _ = _run(model, params)
    rep2, _ = _run(model, params)
    assert rep1.events == rep2.events
    assert rep1.decode_steps == rep2.decode_steps
    for rid in rep1.requests:
        assert rep1.requests[rid].tokens == rep2.requests[rid].tokens
        assert rep1.requests[rid].slot == rep2.requests[rid].slot


def test_slots_are_refilled_mid_flight(setup):
    """Continuous batching: with more requests than slots, some admit
    happens at a decode step > 0 (a freed slot re-enters the batch while
    other slots are mid-generation), every admit/evict pairs up, and a
    slot never holds two live requests."""
    rep, _ = _run(*setup)
    admits = [e for e in rep.events if e[0] == "admit"]
    assert any(e[3] > 0 for e in admits), "no mid-flight refill happened"
    live = {}
    for kind, rid, slot, _step in rep.events:
        if kind == "admit":
            assert slot not in live, f"slot {slot} double-occupied"
            live[slot] = rid
        else:
            assert live.pop(slot) == rid
    assert not live


def test_fifo_admission_order(setup):
    """With all arrivals at t=0, admission order is request id order
    (FIFO with rid tie-break) — the queue head is never bypassed."""
    rep, _ = _run(*setup)
    admitted = [e[1] for e in rep.events if e[0] == "admit"]
    assert admitted == sorted(admitted)


def test_eos_token_stops_early(setup):
    """Re-running with eos_token set to a token the greedy stream is
    known (from a reference run) to emit cuts that request short."""
    model, params = setup
    ref, _ = _run(model, params, n=4, seed=5)
    rid, st = next((r, s) for r, s in ref.requests.items()
                   if len(s.tokens) >= 2)
    eos = st.tokens[0]
    cfg = ServeConfig(slots=3, max_len=64, prefill_chunk=8, eos_token=eos)
    eng = ServingEngine(model, params, cfg)
    reqs, _ = poisson_workload(4, math.inf, 5, vocab_size=V,
                               prompt_len=(2, 12), new_tokens=(3, 8))
    rep = eng.run(reqs)
    st2 = rep.requests[rid]
    assert len(st2.tokens) == 1 and st2.tokens[0] == eos
    for s in rep.requests.values():  # every stream stops at eos or budget
        assert s.tokens[-1] == eos or len(s.tokens) == len(
            ref.requests[s.rid].tokens)


def test_latency_summary_and_throughput(setup):
    rep, _ = _run(*setup, n=5)
    lat = rep.latency_summary()
    for key in ("per_token_p50_s", "per_token_p99_s", "e2e_p50_s",
                "e2e_p99_s", "ttft_p50_s", "ttft_p99_s"):
        assert np.isfinite(lat[key]) and lat[key] >= 0
    assert lat["per_token_p50_s"] <= lat["per_token_p99_s"]
    assert rep.tokens_per_sec > 0
    assert rep.wall_time > 0


def test_oversized_request_rejected(setup):
    model, params = setup
    eng = ServingEngine(model, params,
                        ServeConfig(slots=1, max_len=64, prefill_chunk=8))
    big = Request(rid=0, prompt=np.zeros(60, np.int32), max_new_tokens=10)
    with pytest.raises(ValueError, match="exceeds cache max_len"):
        eng.run([big])


def test_config_validation():
    with pytest.raises(ValueError, match="divide"):
        ServeConfig(slots=2, max_len=64, prefill_chunk=7)
    with pytest.raises(ValueError, match="cache_kind"):
        ServeConfig(cache_kind="fp4")
    with pytest.raises(ValueError, match="slots"):
        ServeConfig(slots=0)


def test_workload_generator_contract():
    reqs, ledger = poisson_workload(6, 2.0, 3, vocab_size=V,
                                    prompt_len=(1, 4), new_tokens=(2, 5))
    arrivals = [r.arrival_time for r in reqs]
    assert arrivals == sorted(arrivals) and arrivals[0] > 0
    reqs2, _ = poisson_workload(6, 2.0, 3, vocab_size=V,
                                prompt_len=(1, 4), new_tokens=(2, 5))
    for a, b in zip(reqs, reqs2):  # same seed → identical stream
        assert a.arrival_time == b.arrival_time
        assert np.array_equal(a.prompt, b.prompt)
        assert a.max_new_tokens == b.max_new_tokens
    for r in reqs:
        assert 1 <= len(r.prompt) <= 4
        assert 2 <= r.max_new_tokens <= 5
        assert ledger[r.rid]["prompt_len"] == len(r.prompt)
