"""Paged KV cache tier: pool primitives, the host-side allocator, and
the engine-level contract that paging is INVISIBLE to the math.

Load-bearing properties:

- pool writes land exactly where the table maps them (flat position =
  table row × page_size + offset), roundtrip per cache kind, and an
  inactive slot's all-zero table row sinks its don't-care writes into
  the reserved garbage page;
- the allocator is deterministic (min-id free heap, oldest-release-first
  retained eviction), all-or-nothing, refcount-correct, and loud on
  double-release;
- the paged engine reproduces the dense engine's greedy logits at every
  step to 1e-5/1e-6 and its scheduler event log byte-for-byte at equal
  capacity — paging changes WHERE bytes live, never what is computed;
- prefix sharing maps already-resident pages instead of re-prefilling
  them without perturbing a single output token;
- SLO admission defers deterministically and never reorders the queue;
- TP × {paged, spec} rejects loudly (ServeCompositionError), and the
  full composed stack (paged + spec + overload guard) is byte-
  deterministic under 2× overload.
"""

import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpudml.core.config import MeshConfig
from tpudml.core.dist import make_mesh
from tpudml.models import TransformerLM
from tpudml.serve import (
    PagePool,
    Request,
    ServeCompositionError,
    ServeConfig,
    ServingEngine,
    SLOConfig,
    init_pool,
    poisson_workload,
    pool_bytes,
)
from tpudml.serve.engine import RequestStats
from tpudml.serve.paged import (
    GARBAGE_PAGE,
    read_row_prefix,
    read_table,
    write_chunk,
    write_tokens,
)
from tpudml.serve.sched import DecodeCostModel

V, D, HEADS, LAYERS, MAX_LEN = 48, 32, 4, 2, 32
RTOL, ATOL = 1e-5, 1e-6

CONFIGS = {
    "rope_dense": dict(rope=True),
    "rope_gqa": dict(rope=True, num_kv_heads=2),
}


def _model(**kw):
    base = dict(vocab_size=V, embed_dim=D, num_heads=HEADS,
                num_layers=LAYERS, max_len=MAX_LEN)
    base.update(kw)
    return TransformerLM(**base)


def _prompt(n=11, seed=3):
    return np.random.default_rng(seed).integers(0, V, n).astype(np.int32)


def full_forward_logits(model, params, prompt, steps):
    """Greedy reference: re-run the FULL forward per emitted token."""
    toks = list(prompt)
    logits_seq, out = [], []
    for _ in range(steps):
        logits, _ = model.apply(params, {}, jnp.asarray([toks], jnp.int32))
        row = np.asarray(logits[0, -1])
        logits_seq.append(row)
        t = int(np.argmax(row))
        toks.append(t)
        out.append(t)
    return logits_seq, out


# ------------------------------------------------------ pool primitives


@pytest.mark.parametrize("kind,tol", [("f32", 0.0), ("bf16", 2e-2),
                                      ("int8", 5e-2)])
def test_pool_write_read_roundtrip(kind, tol):
    """write_chunk + write_tokens land K/V at the table-mapped flat
    positions and read back through read_table/read_row_prefix within
    the kind's storage tolerance; unmapped pages stay zero."""
    rng = np.random.default_rng(0)
    P, M, H, Dh = 4, 3, 2, 8
    pool = init_pool(6, P, H, Dh, kind)
    row = np.array([2, 1, 3], np.int32)  # deliberately non-contiguous
    k_ref = rng.standard_normal((1, M * P, H, Dh)).astype(np.float32)
    v_ref = rng.standard_normal((1, M * P, H, Dh)).astype(np.float32)
    # Prefill positions [0, 8) in two chunks, then decode-write 8..9.
    for s0 in (0, 4):
        pool = write_chunk(pool, jnp.asarray(k_ref[:, s0:s0 + 4]),
                           jnp.asarray(v_ref[:, s0:s0 + 4]),
                           jnp.asarray(row), s0)
    pool = write_tokens(pool, jnp.asarray(k_ref[:, 8:10]),
                        jnp.asarray(v_ref[:, 8:10]),
                        jnp.asarray(row[None, :]),
                        jnp.asarray([8], jnp.int32))
    k, v = read_table(pool, jnp.asarray(row[None, :]), jnp.float32)
    np.testing.assert_allclose(np.asarray(k[0, :10]), k_ref[0, :10],
                               rtol=0, atol=tol)
    np.testing.assert_allclose(np.asarray(v[0, :10]), v_ref[0, :10],
                               rtol=0, atol=tol)
    pk, pv = read_row_prefix(pool, jnp.asarray(row), 10, jnp.float32)
    np.testing.assert_array_equal(np.asarray(pk[0]), np.asarray(k[0, :10]))
    np.testing.assert_array_equal(np.asarray(pv[0]), np.asarray(v[0, :10]))
    # Pages the table never mapped (0, 4, 5) were never written.
    for pid in (GARBAGE_PAGE, 4, 5):
        assert np.all(np.asarray(pool.k[pid]).astype(np.float32) == 0)


def test_inactive_slot_writes_sink_to_garbage_page():
    """An all-zero table row (inactive slot) scatters into page 0 only —
    live pages can never be corrupted by a don't-care slot."""
    pool = init_pool(4, 2, 1, 2, "f32")
    table = jnp.asarray(np.array([[3, 1], [0, 0]], np.int32))
    k = jnp.ones((2, 1, 1, 2))
    pool = write_tokens(pool, k, k, table, jnp.asarray([0, 5], jnp.int32))
    assert np.all(np.asarray(pool.k[3, 0]) == 1)  # live slot landed
    assert np.any(np.asarray(pool.k[GARBAGE_PAGE]) == 1)  # sink took it
    assert np.all(np.asarray(pool.k[2]) == 0)  # unmapped page untouched


def test_pool_validation_and_bytes():
    with pytest.raises(ValueError, match="num_pages"):
        init_pool(1, 4, 2, 8)
    with pytest.raises(ValueError, match="cache kind"):
        init_pool(4, 4, 2, 8, "fp4")
    f32 = init_pool(4, 8, 2, 8, "f32")
    i8 = init_pool(4, 8, 2, 8, "int8")
    assert pool_bytes(i8) < pool_bytes(f32) / 2


# ------------------------------------------------------------ allocator


def test_pagepool_min_id_determinism():
    pool = PagePool(num_pages=6, page_size=4)
    assert pool.alloc_n(3) == [1, 2, 3]  # lowest ids first, in order
    pool.release(2)
    assert pool.alloc_n(2) == [2, 4]  # freed id re-issued before fresh
    assert pool.allocated == 4 and pool.available == 1


def test_pagepool_alloc_is_all_or_nothing():
    pool = PagePool(num_pages=4, page_size=4)  # 3 allocatable pages
    assert pool.alloc_n(2) == [1, 2]
    before = pool.available
    assert pool.alloc_n(2) is None  # would need 2, only 1 left
    assert pool.available == before  # rollback left the pool untouched
    assert pool.alloc_n(1) == [3]
    assert pool.alloc_n(0) == []


def test_pagepool_release_underflow_raises():
    pool = PagePool(num_pages=3, page_size=4)
    (pid,) = pool.alloc_n(1)
    pool.release(pid)
    with pytest.raises(RuntimeError, match="released more"):
        pool.release(pid)


def test_pagepool_prefix_retention_and_lru_eviction():
    """Registered pages survive their last release as retained prefix
    cache, match future admits, and evict oldest-release-first (keys
    unregistered) only when the free heap runs dry."""
    pool = PagePool(num_pages=5, page_size=2, prefix_sharing=True)
    prompt = np.arange(6, dtype=np.int32)  # p=5: pages 0,1 shareable
    pages = pool.alloc_n(3)
    pool.register(pages[0], prompt, 0)
    pool.register(pages[1], prompt, 1)
    # First resident writer wins: a duplicate register is a no-op.
    pool.register(pages[2], prompt, 0)
    for pid in (pages[2], pages[0], pages[1]):  # release order = LRU order
        pool.release(pid)
    assert pool.match_prefix(prompt) == [pages[0], pages[1]]
    # Matching is side-effect-free — the reuse counters belong to the
    # ADMIT (the engine bumps them once admission succeeds), so a
    # page-starved retry can't inflate them.
    assert pool.prefix_hits == 0 and pool.pages_reused == 0
    # Matching does NOT take a reference; acquire does.
    pool.acquire(pages[0])
    pool.acquire(pages[1])
    pool.release(pages[0])
    pool.release(pages[1])
    # Exhaust: free heap first ([3] and [4]), then retained oldest-first.
    assert pool.alloc_n(4) == [pages[2], 4, pages[0], pages[1]]
    assert pool.retained_evictions == 2
    assert pool.match_prefix(prompt) == []  # keys gone with the pages


def test_pagepool_failed_alloc_restores_evicted_retained_pages():
    """A failed (all-or-nothing) alloc_n that evicted retained prefix
    pages mid-attempt must hand back their keys, retained status, and
    LRU order — a deferred admit may not cost the prefix cache
    anything."""
    pool = PagePool(num_pages=4, page_size=2, prefix_sharing=True)
    prompt = np.arange(6, dtype=np.int32)  # p=5: pages 0 and 1 shareable
    pages = pool.alloc_n(3)  # [1, 2, 3]
    pool.register(pages[0], prompt, 0)
    pool.register(pages[1], prompt, 1)
    for pid in pages:
        pool.release(pid)
    # free={3}, retained={1, 2}. Asking for 4 takes 3, evicts 1 then 2,
    # then fails — and the rollback must undo the evictions too.
    assert pool.alloc_n(4) is None
    assert pool.available == 3
    assert pool.retained_evictions == 0
    assert pool.match_prefix(prompt) == [pages[0], pages[1]]
    # Eviction order survives the rollback: free heap first, then the
    # retained pages oldest-release-first, exactly as before the attempt.
    assert pool.alloc_n(3) == [3, pages[0], pages[1]]
    assert pool.retained_evictions == 2


def test_pagepool_match_stops_before_decode_write_position():
    """A page reaching the first decode-write position is not matchable
    — the new request would write into a shared page."""
    pool = PagePool(num_pages=6, page_size=4, prefix_sharing=True)
    long_p = np.arange(9, dtype=np.int32)  # p=8: pages 0 AND 1 end before
    pages = pool.alloc_n(2)
    pool.register(pages[0], long_p, 0)
    pool.register(pages[1], long_p, 1)
    assert pool.match_prefix(long_p) == [pages[0], pages[1]]
    # Same head, one token shorter: p=7, so page 1 (covering positions
    # 4..7) now contains the decode-write position and must not match.
    assert pool.match_prefix(long_p[:8]) == [pages[0]]


# ------------------------------------------------------- engine parity


@pytest.mark.parametrize("cfg", list(CONFIGS), ids=list(CONFIGS))
def test_paged_decode_logits_match_full_forward(cfg):
    """Greedy decode through the page table reproduces the full-forward
    logits at every emitted position — paging is pure data movement."""
    model = _model(**CONFIGS[cfg])
    params, _ = model.init(jax.random.key(0))
    prompt = _prompt()
    scfg = ServeConfig(slots=2, max_len=MAX_LEN, prefill_chunk=4,
                       cache_layout="paged", page_size=4)
    eng = ServingEngine(model, params, scfg)
    st = RequestStats(rid=0, prompt_len=len(prompt), max_new_tokens=9,
                      arrival=0.0)
    pos0, last0 = eng._admit_paged(
        0, Request(rid=0, prompt=prompt, max_new_tokens=9), st)
    ref, toks_ref = full_forward_logits(model, params, prompt, steps=9)
    pos = np.array([pos0, 0], np.int32)
    last = np.array([last0, 0], np.int32)
    for i in range(9):
        next_t, logits, eng.caches = eng._decode(
            eng.params, eng.caches, jnp.asarray(eng._table),
            jnp.asarray(last), jnp.asarray(pos))
        np.testing.assert_allclose(np.asarray(logits[0]), ref[i],
                                   rtol=RTOL, atol=ATOL)
        assert int(next_t[0]) == toks_ref[i]
        last = np.array([int(next_t[0]), 0], np.int32)
        pos = pos + np.array([1, 0], np.int32)


def test_paged_engine_run_matches_dense_run():
    """Same seeded workload, equal capacity: the paged engine's token
    streams AND scheduler event log are identical to the dense engine's
    — the layout never leaks into scheduling."""
    model = _model(rope=True, num_kv_heads=2)
    params, _ = model.init(jax.random.key(1))

    def run(layout):
        cfg = ServeConfig(slots=3, max_len=MAX_LEN, prefill_chunk=4,
                          cache_layout=layout, page_size=4)
        reqs, _ = poisson_workload(8, math.inf, 11, vocab_size=V,
                                   prompt_len=(2, 10), new_tokens=(3, 6))
        return ServingEngine(model, params, cfg).run(reqs)

    dense, paged = run("dense"), run("paged")
    assert dense.events == paged.events
    assert dense.decode_steps == paged.decode_steps
    for rid in dense.requests:
        assert dense.requests[rid].tokens == paged.requests[rid].tokens
    assert paged.pool_stats == {"prefix_hits": 0, "pages_reused": 0,
                                "retained_evictions": 0}


def test_prefix_sharing_reuses_pages_without_changing_tokens():
    """Requests with an equal 12-token head map the head's 3 pages from
    the prefix cache (refcounted, prefill skipped) — and every output
    token still matches the dense engine exactly."""
    model = _model(rope=True, num_kv_heads=2)
    params, _ = model.init(jax.random.key(2))
    head = _prompt(12, seed=21)
    reqs = [Request(rid=i, prompt=np.concatenate(
                [head, _prompt(3, seed=100 + i)]),
                    max_new_tokens=5, arrival_time=0.0)
            for i in range(4)]

    shared_cfg = ServeConfig(slots=2, max_len=MAX_LEN, prefill_chunk=4,
                             cache_layout="paged", page_size=4,
                             prefix_sharing=True)
    rep = ServingEngine(model, params, shared_cfg).run(reqs)
    assert rep.pool_stats["prefix_hits"] == 3  # rids 1..3 hit rid 0's head
    assert rep.pool_stats["pages_reused"] == 9
    assert rep.requests[0].shared_pages == 0
    for rid in (1, 2, 3):
        assert rep.requests[rid].shared_pages == 3

    dense_cfg = ServeConfig(slots=2, max_len=MAX_LEN, prefill_chunk=4)
    ref = ServingEngine(model, params, dense_cfg).run(reqs)
    for rid in ref.requests:
        assert rep.requests[rid].tokens == ref.requests[rid].tokens


def test_prefix_sharing_under_pool_pressure_never_aliases_pages():
    """Admission must acquire matched shared pages BEFORE allocating the
    fresh ones: a pressured alloc evicts retained pages oldest-first,
    and without the acquire it can hand a just-matched page back as a
    'fresh' page — the same pool page mapped at two table rows, so
    decode writes silently corrupt the prompt K/V the request attends
    over. Here the pool is sized so rid 2's admission finds exactly its
    two matched pages in the retained LRU and only one free page: the
    admit must DEFER (leaving the prefix cache intact) and succeed once
    rid 1's pages free up, with every token still dense-exact."""
    model = _model(rope=True, num_kv_heads=2)
    params, _ = model.init(jax.random.key(4))
    head = _prompt(9, seed=31)
    reqs = [
        # rid 0: 3 pages [1,2,3]; registers pages 0..1 of the head, done
        # after 2 decode steps — pages 1,2 go RETAINED, page 3 frees.
        Request(rid=0, prompt=head, max_new_tokens=2, arrival_time=0.0),
        # rid 1: 2 pages [4,5], still running when rid 2 arrives.
        Request(rid=1, prompt=_prompt(5, seed=32), max_new_tokens=3,
                arrival_time=0.0),
        # rid 2: shares the 8-token head (matches retained pages 1,2) and
        # needs 2 fresh pages with only page 3 free — the pressure case.
        Request(rid=2, prompt=np.concatenate([head[:8], _prompt(4, seed=33)]),
                max_new_tokens=4, arrival_time=2.0),
    ]
    cfg = ServeConfig(slots=2, max_len=MAX_LEN, prefill_chunk=4,
                      cache_layout="paged", page_size=4,
                      prefix_sharing=True, num_pages=6, step_time_s=1.0)
    rep = ServingEngine(model, params, cfg).run(reqs)
    assert ("defer", 2, -1, 2) in rep.events  # page-starved, not aliased
    assert rep.requests[2].shared_pages == 2
    # A deferred-then-retried admit counts its prefix hit exactly once.
    assert rep.pool_stats["prefix_hits"] == 1
    assert rep.pool_stats["pages_reused"] == 2
    assert rep.pool_stats["retained_evictions"] == 0

    dense_cfg = ServeConfig(slots=2, max_len=MAX_LEN, prefill_chunk=4,
                            step_time_s=1.0)
    ref = ServingEngine(model, params, dense_cfg).run(reqs)
    for rid in ref.requests:
        assert rep.requests[rid].tokens == ref.requests[rid].tokens


# -------------------------------------------------------- SLO admission


def test_slo_admission_defers_deterministically():
    """A budget sized between the 1-active and 2-active step price
    serializes the engine to one tenant at a time: defers are logged,
    FIFO order survives, nothing starves, and the run is deterministic."""
    model = _model(rope=True, num_kv_heads=2)
    params, _ = model.init(jax.random.key(3))
    base = ServeConfig(slots=3, max_len=MAX_LEN, prefill_chunk=4,
                       step_time_s=0.01)
    probe = DecodeCostModel(model, base, SLOConfig(tpot_budget_s=1.0))
    budget = (probe.step_seconds(1) + probe.step_seconds(2)) / 2
    cfg = ServeConfig(slots=3, max_len=MAX_LEN, prefill_chunk=4,
                      step_time_s=0.01, slo=SLOConfig(tpot_budget_s=budget))

    def once():
        reqs = [Request(rid=i, prompt=_prompt(6, seed=i),
                        max_new_tokens=4, arrival_time=0.0)
                for i in range(4)]
        return ServingEngine(model, params, cfg).run(reqs)

    rep = once()
    assert any(e[0] == "defer" for e in rep.events)
    admitted = [e[1] for e in rep.events if e[0] == "admit"]
    assert admitted == [0, 1, 2, 3]  # FIFO preserved through deferral
    live = set()
    for e in rep.events:
        if e[0] == "admit":
            assert not live, "SLO budget admitted a second tenant"
            live.add(e[1])
        elif e[0] == "evict":
            live.remove(e[1])
    for st in rep.requests.values():
        assert st.finished is not None and len(st.tokens) == 4
    rep2 = once()
    assert rep.events == rep2.events


def test_page_starved_admission_defers_then_completes():
    """A pool too small for two tenants defers the queue head (event
    logged once) until the running tenant releases its pages; everyone
    still finishes with exact token counts."""
    model = _model(rope=True, num_kv_heads=2)
    params, _ = model.init(jax.random.key(4))
    # Each request needs ceil((6+4)/4) = 3 pages; pool holds 4.
    cfg = ServeConfig(slots=2, max_len=MAX_LEN, prefill_chunk=4,
                      cache_layout="paged", page_size=4, num_pages=5,
                      step_time_s=0.01)
    reqs = [Request(rid=i, prompt=_prompt(6, seed=50 + i),
                    max_new_tokens=4, arrival_time=0.0) for i in range(3)]
    rep = ServingEngine(model, params, cfg).run(reqs)
    defers = [e for e in rep.events if e[0] == "defer"]
    assert defers and len({e[1] for e in defers}) == len(defers)  # deduped
    for st in rep.requests.values():
        assert st.finished is not None and len(st.tokens) == 4


def test_impossible_page_demand_raises_at_idle():
    """A request that can NEVER fit the pool raises instead of
    deadlocking the queue (deferral only makes sense with someone to
    wait for)."""
    model = _model(rope=True, num_kv_heads=2)
    params, _ = model.init(jax.random.key(5))
    cfg = ServeConfig(slots=1, max_len=MAX_LEN, prefill_chunk=4,
                      cache_layout="paged", page_size=4, num_pages=3)
    eng = ServingEngine(model, params, cfg)
    big = Request(rid=0, prompt=_prompt(20, seed=9), max_new_tokens=8)
    with pytest.raises(ValueError, match="pool can ever supply"):
        eng.run([big])


# ----------------------------------------------------------- composition


def test_tp_rejects_paged_and_spec():
    mesh = make_mesh(MeshConfig({"model": 2}), jax.devices()[:2])
    model = _model(rope=True, num_kv_heads=2)
    params, _ = model.init(jax.random.key(0))
    paged = ServeConfig(slots=2, max_len=MAX_LEN, prefill_chunk=4,
                        cache_layout="paged", page_size=4)
    with pytest.raises(ServeCompositionError, match="paged"):
        ServingEngine(model, params, paged, mesh=mesh, axis_name="model")
    spec = ServeConfig(slots=2, max_len=MAX_LEN, prefill_chunk=4, spec_k=2)
    with pytest.raises(ServeCompositionError, match="spec_k"):
        ServingEngine(model, params, spec, mesh=mesh, axis_name="model")


def test_tpserving_guard_rejects_directly():
    """The TPServing constructor itself refuses non-dense configs —
    defense in depth if someone bypasses ServingEngine."""
    from tpudml.serve.tp import TPServing

    mesh = make_mesh(MeshConfig({"model": 2}), jax.devices()[:2])
    model = _model(rope=True, num_kv_heads=2)
    cfg = ServeConfig(slots=2, max_len=MAX_LEN, prefill_chunk=4,
                      cache_layout="paged", page_size=4)
    with pytest.raises(ServeCompositionError, match="dense"):
        TPServing(model, mesh, "model", cfg)


# ------------------------------------------------- golden determinism


def test_paged_spec_overload_run_is_byte_deterministic():
    """The fully composed stack — paged cache + speculative decoding +
    bounded queue at 2× overload on the virtual clock — reproduces a
    byte-identical event log and token streams across runs."""
    model = _model(rope=True, num_kv_heads=2)
    params, _ = model.init(jax.random.key(6))
    cfg = ServeConfig(slots=1, max_len=MAX_LEN, prefill_chunk=4,
                      cache_layout="paged", page_size=4, spec_k=2,
                      max_queue=2, step_time_s=0.01)

    def once():
        reqs, _ = poisson_workload(10, 40.0, seed=5, vocab_size=V,
                                   prompt_len=(2, 6), new_tokens=(8, 8))
        return ServingEngine(model, params, cfg, draft_layers=1).run(reqs)

    a, b = once(), once()
    assert repr(a.events).encode() == repr(b.events).encode()
    assert a.decode_steps == b.decode_steps
    assert a.rejected == b.rejected and a.rejected > 0  # guard engaged
    for rid in a.requests:
        assert a.requests[rid].tokens == b.requests[rid].tokens
    assert any(e[0] == "spec" for e in a.events)
